"""SegmentMatcher: the framework's matcher facade.

API-compatible with the surface the reference uses from the ``valhalla``
extension module (reference: py/reporter_service.py:21,52,240 and
py/simple_reporter.py:132-133):

    Configure(config_path_or_dict)
    m = SegmentMatcher()
    match_json = m.Match(trace_json_str)

plus the batched entry point the reference lacks — ``match_many`` — which is
the TPU hot path: many traces prepared on host, decoded in one vmapped
Viterbi per padding bucket.
"""
from __future__ import annotations

import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np

from ..graph.network import RoadNetwork
from ..graph.route import RouteCache
from ..graph.spatial import SpatialGrid
from .assemble import assemble_segments
from .batchpad import pack_batches, prepare_trace
from .params import MatchParams

# process-wide configuration, mirroring valhalla.Configure's module-level
# behavior (reference: reporter_service.py:284)
_global_config: dict = {}


def _decode_chunk() -> int:
    """Chunk size for the decode dispatch pipeline (env-tunable)."""
    try:
        return max(1, int(os.environ.get("REPORTER_TPU_DECODE_CHUNK", 128)))
    except ValueError:
        return 128


def _prep_workers() -> int:
    """Host-prep thread count (env-tunable; 0 disables the pool)."""
    try:
        return int(os.environ.get("REPORTER_TPU_PREP_THREADS",
                                  min(32, os.cpu_count() or 1)))
    except ValueError:
        return min(32, os.cpu_count() or 1)


def Configure(conf) -> None:
    """Load matcher configuration from a JSON file path or a dict.

    Recognised keys (all optional): ``graph`` (path to a RoadNetwork .npz),
    and any MatchParams field under ``matcher`` (sigma_z, beta, ...).
    """
    global _global_config
    if isinstance(conf, str):
        with open(conf) as f:
            _global_config = json.load(f)
    else:
        _global_config = dict(conf)


class SegmentMatcher:
    """Batched HMM matcher bound to one road network.

    One instance serves the whole process (the reference instead creates
    one C++ matcher per service thread, reporter_service.py:51-58). The
    service serialises device work through its BatchDispatcher thread;
    direct concurrent Match() calls are safe under CPython's GIL (the
    shared RouteCache may redundantly recompute but never corrupts).
    """

    def __init__(self, net: Optional[RoadNetwork] = None,
                 params: Optional[MatchParams] = None,
                 grid_cell_m: float = 250.0,
                 use_native: Optional[bool] = None):
        if net is None:
            graph_path = _global_config.get("graph")
            if graph_path is None:
                raise ValueError(
                    "no network: pass net= or Configure({'graph': path})")
            net = RoadNetwork.load(graph_path)
        self.net = net
        if params is None:
            params = MatchParams(**_global_config.get("matcher", {}))
        self.params = params
        self._grid_cell_m = grid_cell_m
        # the numpy structures are only built if the fallback path is used
        # (the native runtime owns its own grid and cache)
        self._grid: Optional[SpatialGrid] = None
        self._route_cache: Optional[RouteCache] = None
        # C++ host runtime when available (and not explicitly disabled);
        # numpy fallback otherwise — identical contract
        self.runtime = None
        if use_native is not False:
            from .. import native
            if native.available():
                self.runtime = native.NativeRuntime(net, cell_m=grid_cell_m)
            elif use_native:
                raise RuntimeError("native host runtime requested but "
                                   "unavailable")
        # shared prep pool, created lazily on the first batched call.
        # Safe for both prep paths: the C++ runtime releases the GIL and
        # stripe-locks its route cache; the numpy path's RouteCache dict
        # ops are atomic under the GIL (races cost a redundant dijkstra,
        # never corruption).
        self._prep_pool: Optional[ThreadPoolExecutor] = None

    @property
    def grid(self) -> SpatialGrid:
        if self._grid is None:
            self._grid = SpatialGrid(self.net, cell_m=self._grid_cell_m)
        return self._grid

    @property
    def route_cache(self) -> RouteCache:
        if self._route_cache is None:
            self._route_cache = RouteCache(self.net)
        return self._route_cache

    # -- single-trace, reference-shaped API --------------------------------
    def Match(self, trace_json: str) -> str:
        trace = json.loads(trace_json)
        result = self.match_many([trace])[0]
        return json.dumps(result, separators=(",", ":"))

    # -- batched hot path --------------------------------------------------
    def prepare(self, points: Sequence[dict],
                params: Optional[MatchParams] = None):
        """Host prep (candidates + route tensors) for one trace — the
        single owner of the native-vs-numpy dispatch; bench and tests use
        this instead of re-implementing the branch."""
        params = params if params is not None else self.params
        if self.runtime is not None:
            return prepare_trace(self.net, None, points, params,
                                 runtime=self.runtime)
        return prepare_trace(self.net, self.grid, points, params,
                             self.route_cache)

    def _prepare_one(self, item):
        """(index, trace, params) -> (index, PreparedTrace)."""
        i, tr, params = item
        return i, self.prepare(tr["trace"], params)

    def _prep_map(self, items):
        """Prepare a chunk of (index, trace, params), in parallel when the
        native runtime is present. Host prep (candidates + bounded
        Dijkstra) is the end-to-end ceiling, not the decode — this is
        where the reference's 16-process fan-out
        (simple_reporter.py:265-297) is matched, with threads against the
        GIL-releasing, lock-striped C++ runtime instead of processes.
        The pure-Python numpy fallback holds the GIL, so threads would
        only add contention there — it stays serial."""
        workers = _prep_workers()
        if self.runtime is None or workers <= 1 or len(items) <= 1:
            return [self._prepare_one(it) for it in items]
        if self._prep_pool is None:
            self._prep_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="prep")
        return list(self._prep_pool.map(self._prepare_one, items))

    def match_many(self, traces: Sequence[dict]) -> List[dict]:
        """Match a batch of trace dicts; returns match dicts in order.

        Each trace: {"uuid": ..., "trace": [{lat, lon, time, ...}, ...],
        "match_options": {...}} — per-trace match_options may override
        params (reference: generate_test_trace.py:45-52).

        Three-stage pipeline per chunk: host prep on the thread pool,
        async device decode dispatch, host assembly after the last
        dispatch — so chunk N+1's prep overlaps chunk N's decode, and
        decode of late chunks overlaps assembly of early ones.
        """
        per_trace_params = [
            self.params.with_options(tr.get("match_options", {}))
            for tr in traces]

        # deferred: importing at module level would cycle through
        # ops -> pallas_viterbi -> matcher.hmm -> matcher/__init__
        from ..ops import batch_pad_multiple, decode_batch

        # sigma/beta are batch-wide scalars on device, so traces may only
        # share a batch when their scoring params agree — group first, then
        # bucket by length within each group
        groups: dict[tuple, list] = {}
        for i, (tr, params) in enumerate(zip(traces, per_trace_params)):
            key = (params.effective_sigma, params.beta)
            groups.setdefault(key, []).append((i, tr, params))

        chunk = _decode_chunk()
        # pad the batch dim to the mesh's data-axis size so decode_batch
        # takes the sharded multi-device path (filler rows are all-SKIP
        # traces that decode to nothing)
        pad = batch_pad_multiple()
        if pad:
            chunk = ((chunk + pad - 1) // pad) * pad

        # chunked pipeline: prep chunk (parallel) -> enqueue decode + async
        # d2h copy -> prep next chunk while the device works. Nothing is
        # drained until every chunk is dispatched, so h2d, decode and d2h of
        # later chunks overlap host prep/assembly of earlier ones.
        prepared: dict[int, object] = {}
        pending = []
        for (sigma, beta), items in groups.items():
            for lo in range(0, len(items), chunk):
                prepped = self._prep_map(items[lo:lo + chunk])
                for i, p in prepped:
                    prepared[i] = p
                group = [p for _i, p in prepped]
                order = [i for i, _p in prepped]
                for batch in pack_batches(group, pad_batch_to=pad,
                                          pad_pow2=True):
                    decoded, _scores = decode_batch(
                        batch.dist_m, batch.valid, batch.route_m,
                        batch.gc_m, batch.case,
                        np.float32(sigma), np.float32(beta))
                    if hasattr(decoded, "copy_to_host_async"):
                        decoded.copy_to_host_async()
                    pending.append((batch, order, decoded))

        paths: dict[int, np.ndarray] = {}
        for batch, order, decoded in pending:
            decoded = np.asarray(decoded)
            idx_of = {id(prepared[i]): i for i in order}
            for b, p in enumerate(batch.traces):
                paths[idx_of[id(p)]] = decoded[b]

        results = []
        for i, (tr, params) in enumerate(zip(traces, per_trace_params)):
            results.append(assemble_segments(
                self.net, prepared[i], paths[i], mode=params.mode,
                queue_threshold_kph=params.queue_speed_threshold_kph,
                interpolation_distance_m=params.interpolation_distance))
        return results
