"""SegmentMatcher: the framework's matcher facade.

API-compatible with the surface the reference uses from the ``valhalla``
extension module (reference: py/reporter_service.py:21,52,240 and
py/simple_reporter.py:132-133):

    Configure(config_path_or_dict)
    m = SegmentMatcher()
    match_json = m.Match(trace_json_str)

plus the batched entry point the reference lacks — ``match_many`` — which is
the TPU hot path: many traces prepared on host, decoded in one vmapped
Viterbi per padding bucket.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np

from ..graph.network import RoadNetwork
from ..graph.route import RouteCache
from ..graph.spatial import SpatialGrid
from .assemble import assemble_segments
from .batchpad import pack_batches, prepare_trace
from .params import MatchParams

# process-wide configuration, mirroring valhalla.Configure's module-level
# behavior (reference: reporter_service.py:284)
_global_config: dict = {}


def _decode_chunk() -> int:
    """Chunk size for the decode dispatch pipeline (env-tunable)."""
    try:
        return max(1, int(os.environ.get("REPORTER_TPU_DECODE_CHUNK", 128)))
    except ValueError:
        return 128


def Configure(conf) -> None:
    """Load matcher configuration from a JSON file path or a dict.

    Recognised keys (all optional): ``graph`` (path to a RoadNetwork .npz),
    and any MatchParams field under ``matcher`` (sigma_z, beta, ...).
    """
    global _global_config
    if isinstance(conf, str):
        with open(conf) as f:
            _global_config = json.load(f)
    else:
        _global_config = dict(conf)


class SegmentMatcher:
    """Batched HMM matcher bound to one road network.

    One instance serves the whole process (the reference instead creates
    one C++ matcher per service thread, reporter_service.py:51-58). The
    service serialises device work through its BatchDispatcher thread;
    direct concurrent Match() calls are safe under CPython's GIL (the
    shared RouteCache may redundantly recompute but never corrupts).
    """

    def __init__(self, net: Optional[RoadNetwork] = None,
                 params: Optional[MatchParams] = None,
                 grid_cell_m: float = 250.0,
                 use_native: Optional[bool] = None):
        if net is None:
            graph_path = _global_config.get("graph")
            if graph_path is None:
                raise ValueError(
                    "no network: pass net= or Configure({'graph': path})")
            net = RoadNetwork.load(graph_path)
        self.net = net
        if params is None:
            params = MatchParams(**_global_config.get("matcher", {}))
        self.params = params
        self._grid_cell_m = grid_cell_m
        # the numpy structures are only built if the fallback path is used
        # (the native runtime owns its own grid and cache)
        self._grid: Optional[SpatialGrid] = None
        self._route_cache: Optional[RouteCache] = None
        # C++ host runtime when available (and not explicitly disabled);
        # numpy fallback otherwise — identical contract
        self.runtime = None
        if use_native is not False:
            from .. import native
            if native.available():
                self.runtime = native.NativeRuntime(net, cell_m=grid_cell_m)
            elif use_native:
                raise RuntimeError("native host runtime requested but "
                                   "unavailable")

    @property
    def grid(self) -> SpatialGrid:
        if self._grid is None:
            self._grid = SpatialGrid(self.net, cell_m=self._grid_cell_m)
        return self._grid

    @property
    def route_cache(self) -> RouteCache:
        if self._route_cache is None:
            self._route_cache = RouteCache(self.net)
        return self._route_cache

    # -- single-trace, reference-shaped API --------------------------------
    def Match(self, trace_json: str) -> str:
        trace = json.loads(trace_json)
        result = self.match_many([trace])[0]
        return json.dumps(result, separators=(",", ":"))

    # -- batched hot path --------------------------------------------------
    def match_many(self, traces: Sequence[dict]) -> List[dict]:
        """Match a batch of trace dicts; returns match dicts in order.

        Each trace: {"uuid": ..., "trace": [{lat, lon, time, ...}, ...],
        "match_options": {...}} — per-trace match_options may override
        params (reference: generate_test_trace.py:45-52).
        """
        prepared = []
        per_trace_params = []
        for tr in traces:
            params = self.params.with_options(tr.get("match_options", {}))
            per_trace_params.append(params)
            if self.runtime is not None:
                prepared.append(prepare_trace(
                    self.net, None, tr["trace"], params,
                    runtime=self.runtime))
            else:
                prepared.append(prepare_trace(
                    self.net, self.grid, tr["trace"], params,
                    self.route_cache))

        # deferred: importing at module level would cycle through
        # ops -> pallas_viterbi -> matcher.hmm -> matcher/__init__
        from ..ops import batch_pad_multiple, decode_batch

        # sigma/beta are batch-wide scalars on device, so traces may only
        # share a batch when their scoring params agree — group first, then
        # bucket by length within each group
        paths: dict[int, np.ndarray] = {}
        index_of = {id(p): i for i, p in enumerate(prepared)}
        groups: dict[tuple, list] = {}
        for p, params in zip(prepared, per_trace_params):
            key = (params.effective_sigma, params.beta)
            groups.setdefault(key, []).append(p)
        # two-phase dispatch: enqueue every chunk's decode + its async
        # device->host copy before draining any, so transfer and compute of
        # later chunks overlap host-side work on earlier ones (the h2d copy
        # is the bottleneck on tunneled chips, not the decode itself)
        chunk = _decode_chunk()
        # pad the batch dim to the mesh's data-axis size so decode_batch
        # takes the sharded multi-device path (filler rows are all-SKIP
        # traces that decode to nothing)
        pad = batch_pad_multiple()
        if pad:
            chunk = ((chunk + pad - 1) // pad) * pad
        pending = []
        for (sigma, beta), group in groups.items():
            for batch in pack_batches(group, pad_batch_to=pad,
                                      max_batch=chunk):
                decoded, _scores = decode_batch(
                    batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
                    batch.case, np.float32(sigma), np.float32(beta))
                if hasattr(decoded, "copy_to_host_async"):
                    decoded.copy_to_host_async()
                pending.append((batch, decoded))
        for batch, decoded in pending:
            decoded = np.asarray(decoded)
            for b, ptrace in enumerate(batch.traces):
                paths[index_of[id(ptrace)]] = decoded[b]

        results = []
        for i, (tr, ptrace) in enumerate(zip(traces, prepared)):
            params = per_trace_params[i]
            results.append(assemble_segments(
                self.net, ptrace, paths[i], mode=params.mode,
                queue_threshold_kph=params.queue_speed_threshold_kph,
                interpolation_distance_m=params.interpolation_distance))
        return results
