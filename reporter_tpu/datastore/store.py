"""Append-only columnar histogram store, partitioned by graph tile.

Layout on disk (one partition per ``(level, tile_index)`` — the same key
the anonymiser's flush paths and the OSMLR id low bits use)::

    <root>/<level>/<tile_index>/
        MANIFEST.json            {"seq": N, "segments": ["base-…", "delta-…"]}
        delta-000001/            one committed aggregation increment
            hist_key.npy         sorted int64 composite keys (schema.py)
            hist_count.npy       int64
            hist_speed_sum.npy   float64
            trans_from.npy       int64 (sorted pairs)
            trans_to.npy         int64
            trans_count.npy      int64
            meta.json
        base-000007/             compaction output (same columns)

Commit protocol (single-writer per process, lock-held; crash-safe):
arrays are written into a dot-prefixed temp dir in the partition, then
``os.replace``'d to the final segment name, then the manifest is
rewritten via temp-file + ``os.replace``. A reader loads the manifest
and mmaps only segments it lists, so a half-written segment is never
visible and a crashed commit leaves only an ignorable temp dir.

Reads are ``np.load(..., mmap_mode="r")`` — a query touches the pages of
one binary-searched key range per segment file, not the whole partition.
Compaction merges every live segment into a single ``base-`` segment and
then deletes the merged dirs; concurrent readers holding the old
manifest keep valid mmaps (POSIX unlink semantics).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import shutil
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils import faults, fsio, metrics
from ..utils import locks as _locks
from .aggregate import Delta, aggregate, merge_deltas
from .lease import StoreLease
from .schema import ObservationBatch

logger = logging.getLogger("reporter_tpu.datastore")

MANIFEST = "MANIFEST.json"

#: per-process stage-dir sequence: itertools.count() is atomic under
#: the GIL, so concurrent unlocked stagers in one process never collide
#: (cross-process collisions are excluded by the pid in the name)
_STAGE_IDS = itertools.count()


def _ledger_cap() -> int:
    """Per-partition ``ingested``-ledger size cap (0 = unbounded)."""
    from ..utils.runtime import _env_int
    return _env_int("REPORTER_TPU_INGEST_LEDGER_MAX", 4096)

_COLUMNS = (
    ("hist_key", np.int64),
    ("hist_count", np.int64),
    ("hist_speed_sum", np.float64),
    ("trans_from", np.int64),
    ("trans_to", np.int64),
    ("trans_count", np.int64),
)


def pressure_exceeded(n_deltas: int, delta_bytes: int,
                      max_deltas: Optional[int],
                      max_delta_bytes: Optional[int]) -> bool:
    """THE compaction-pressure predicate — one definition shared by
    the store's automatic policy and the background compactor's
    backlog gauge, so the gauge can never report pressure the policy
    would not compact (or vice versa)."""
    return ((max_deltas is not None and n_deltas > max_deltas)
            or (max_delta_bytes is not None
                and delta_bytes > max_delta_bytes))


class HistogramStore:
    """The local datastore: ingest observation batches, serve mmap'd
    deltas to the query layer, compact partitions in place.

    Reads go through a bounded partition-handle LRU: one /histogram
    request used to re-``np.load``/mmap every segment file of the
    partition (ROADMAP's named serving-scale gap). Handles are keyed by
    the manifest's segment list, so any committed append or compaction
    invalidates naturally on the next read — the manifest itself is
    still read per request (a tiny JSON open; it IS the invalidation
    signal), only the mmap opens are amortised. Hit/miss counts surface
    as ``datastore.query.cache.*`` on /stats.
    """

    def __init__(self, root: str, handle_cache_size: Optional[int] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # long_hold_ok: the fsync-heavy segment STAGING runs unlocked
        # (the runtime witness drove that split — see append()), but the
        # residual critical section is the commit protocol itself:
        # manifest read -> rename+dir-fsync -> atomic manifest write.
        # Those fsyncs are the durability barrier and are irreducibly
        # disk-bound (hundreds of ms on a loaded box), and serialising
        # commits per store is the design — the same documented-long-
        # holder class as the native once-only build lock.
        self._lock = _locks.new_lock("datastore.store", long_hold_ok=True)
        if handle_cache_size is None:
            from ..utils.runtime import _env_int
            handle_cache_size = _env_int(
                "REPORTER_TPU_DATASTORE_HANDLES", 64)
        self.handle_cache_size = max(0, handle_cache_size)
        self._handle_lock = _locks.new_lock("datastore.handles")
        # (pdir, (segment names...)) -> [Delta] of live mmap handles
        self._handles: "OrderedDict[tuple, List[Delta]]" = OrderedDict()
        # (pdir, (segment names...)) -> int64 resident segment ids —
        # the bbox query's enumeration, cached under the same
        # manifest-content key (and bound) as the handles: recomputing
        # it per request would rescan every live file's whole key
        # column at dashboard QPS
        self._resident_ids: "OrderedDict[tuple, np.ndarray]" = \
            OrderedDict()
        # cross-process writer lease (lease.py): every mutating entry
        # point below must hold it — prefork slots, the drainer and the
        # worker tee can all point at this root at once, and the
        # in-process _lock above cannot see the other processes
        self.lease = StoreLease(root)
        # optional freshness tier (freshness.py, attached via
        # LocalDatastore.enable_freshness): ingest() records every
        # per-partition delta here so window=/feed surfaces see a
        # flush the instant it lands, whatever producer drove it
        self.freshness = None
        # active graph epoch (graph/version.py): owners serving a
        # versioned graph set this (CityRegistry loads, the swap chaos
        # harness); when set, commits stamp it into the manifest +
        # per-segment epoch map and the ingest-ledger key so histograms
        # can never silently mix observations from two map builds.
        # None (the default, every pre-versioning producer) keeps the
        # layout and ledger keys byte-identical to before
        self.map_version: Optional[str] = None

    def set_map_version(self, version: Optional[str]) -> None:
        self.map_version = str(version) if version else None

    def _epoch_key(self, ingest_key: Optional[str]) -> Optional[str]:
        """The effective exactly-once ledger key: the flush identity,
        epoch-qualified when this store serves a versioned graph. The
        same tile re-offered under a NEW map build is new data (its
        segments were matched against different geometry), so it must
        not dedupe against the old epoch's commit."""
        if ingest_key is None or self.map_version is None:
            return ingest_key
        suffix = f"@{self.map_version}"
        # idempotent: ingest() qualifies before the freshness hook and
        # append() qualifies again — both must agree on one spelling
        return ingest_key if ingest_key.endswith(suffix) \
            else ingest_key + suffix

    # -- paths -------------------------------------------------------------
    def partition_dir(self, level: int, index: int) -> str:
        return os.path.join(self.root, str(int(level)), str(int(index)))

    def partitions(self) -> Iterator[Tuple[int, int]]:
        """All (level, tile_index) partitions present on disk."""
        try:
            levels = sorted(d for d in os.listdir(self.root)
                            if d.isdigit())
        except FileNotFoundError:
            return
        for lvl in levels:
            ldir = os.path.join(self.root, lvl)
            for idx in sorted(d for d in os.listdir(ldir) if d.isdigit()):
                if os.path.exists(os.path.join(ldir, idx, MANIFEST)):
                    yield int(lvl), int(idx)

    # -- manifest ----------------------------------------------------------
    def _read_manifest(self, pdir: str) -> dict:
        try:
            with open(os.path.join(pdir, MANIFEST), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return {"seq": 0, "segments": []}

    def _write_manifest(self, pdir: str, manifest: dict) -> None:
        # the manifest IS the commit point: tmp + fsync + replace + dir
        # fsync (fsio), so a power loss can neither tear it nor lose
        # the rename (reporter-lint DUR002/DUR003)
        fsio.atomic_write_text(os.path.join(pdir, MANIFEST),
                               json.dumps(manifest))

    # -- write path --------------------------------------------------------
    def append(self, level: int, index: int, delta: Delta,
               ingest_key: Optional[str] = None) -> Optional[str]:
        """Commit one delta as a new immutable segment; returns its name.

        ``ingest_key`` is the exactly-once idempotency key (ISSUE 9):
        the flush-file identity ``{t0}_{t1}/{level}/{tile}/{source}
        [.writer].e{epoch}`` every producer path derives the same way —
        the worker tee from its flush epoch, directory replays from the
        tile file's relpath. The partition manifest carries an
        ``ingested`` ledger of keys it has committed; a key already in
        the ledger makes this append a counted no-op (returns None), so
        a crash-replayed tee flush or an interrupted ``ingest --delete``
        re-run leaves the store BYTE-IDENTICAL instead of double
        counting. Ledger entry and segment commit share the one atomic
        manifest write, so there is no window where one is durable
        without the other.

        The ledger is bounded: a long-lived tee adds one key per flush
        per touched partition forever, and the whole manifest is
        re-serialised on every append, so an unbounded ledger turns
        into O(n^2) cumulative manifest I/O. Beyond
        ``REPORTER_TPU_INGEST_LEDGER_MAX`` keys (insertion-ordered;
        oldest evicted first, ``datastore.ingest.ledger_evicted``) the
        dedupe window slides: the newest N flushes per partition stay
        idempotent — replays older than that must rely on ``ingest
        --delete`` having removed their files."""
        # failure domain: a failed commit surfaces to the caller (the
        # worker tee logs-and-continues; `datastore ingest` quarantines
        # the tile) and the crash-safe protocol below leaves only an
        # ignorable temp dir behind
        faults.failpoint("datastore.commit")
        # cross-process gate FIRST: a non-holder must refuse before any
        # staging I/O — the tee catches LeaseHeldElsewhere and spools
        # the tile body for replay, it never risks a manifest commit
        # interleaved with the live holder's
        self.lease.require()
        # epoch-qualify the ledger key up front so every use below —
        # pre-check, authoritative re-check, ledger insert — sees one
        # spelling (a None map_version leaves the key untouched)
        ingest_key = self._epoch_key(ingest_key)
        with metrics.timer("datastore.store.append"):
            pdir = self.partition_dir(level, index)
            os.makedirs(pdir, exist_ok=True)
            # unlocked dedupe pre-check: a replayed flush (the dead-
            # letter drainer's common case) must not pay a whole
            # segment's staging I/O just to be thrown away — the
            # authoritative re-check under the lock below still owns
            # correctness against a racing first ingest
            if ingest_key is not None \
                    and ingest_key in self._read_manifest(pdir).get(
                        "ingested", {}):
                metrics.count("datastore.ingest.deduped")
                logger.info("dedupe: %s already ingested into %d/%d; "
                            "skipping", ingest_key, level, index)
                return None
            # stage the fsync-heavy column writes OUTSIDE the store
            # lock (the runtime witness flagged the old lock-held
            # protocol as RC002: whole-segment disk I/O under the lock
            # stalled every concurrent append/compaction); the lock
            # covers only manifest read -> rename -> manifest commit
            tmp = self._stage_segment(pdir, delta)
            with self._lock:
                manifest = self._read_manifest(pdir)
                if ingest_key is not None \
                        and ingest_key in manifest.get("ingested", {}):
                    metrics.count("datastore.ingest.deduped")
                    logger.info("dedupe: %s already ingested into %d/%d "
                                "(segment %s); skipping", ingest_key,
                                level, index,
                                manifest["ingested"][ingest_key])
                    shutil.rmtree(tmp, ignore_errors=True)
                    return None
                seq = manifest["seq"] + 1
                name = f"delta-{seq:06d}"
                self._commit_segment(pdir, tmp, name)
                self._check_seq_fence(pdir, seq - 1)
                manifest["seq"] = seq
                manifest["segments"] = manifest["segments"] + [name]
                if self.map_version is not None:
                    # epoch stamp: the manifest's map_version is the
                    # active epoch, ``epochs`` tags each segment with
                    # the build that produced it — queries pin on it
                    # (EpochView) and compaction never merges across it
                    manifest["map_version"] = self.map_version
                    epochs = dict(manifest.get("epochs", {}))
                    epochs[name] = self.map_version
                    manifest["epochs"] = epochs
                    metrics.count("datastore.epoch.stamped_segments")
                if ingest_key is not None:
                    ingested = dict(manifest.get("ingested", {}))
                    ingested[ingest_key] = name
                    cap = _ledger_cap()
                    if cap and len(ingested) > cap:
                        evicted = len(ingested) - cap
                        for old in list(ingested)[:evicted]:
                            del ingested[old]
                        metrics.count("datastore.ingest.ledger_evicted",
                                      evicted)
                    manifest["ingested"] = ingested
                self._write_manifest(pdir, manifest)
                return name

    def _stage_segment(self, pdir: str, delta: Delta) -> str:
        """Write one segment's columns into a dot-prefixed temp dir,
        every file fsync'd — run UNLOCKED (this is the long disk I/O).
        The temp name is pid- and counter-qualified so concurrent
        stagers never collide; a crash leaves only this ignorable dir."""
        tmp = os.path.join(
            pdir, f".tmp-{os.getpid()}-{next(_STAGE_IDS)}")
        os.makedirs(tmp)
        for col, dtype in _COLUMNS:
            col_path = os.path.join(tmp, col + ".npy")
            np.save(col_path,
                    np.ascontiguousarray(getattr(delta, col), dtype=dtype))
            fsio.fsync_path(col_path)
        tmp_meta = os.path.join(tmp, "meta.json")
        with open(tmp_meta, "w", encoding="utf-8") as f:
            json.dump({"cells": len(delta), "rows": delta.rows,
                       "transitions": int(delta.trans_from.shape[0]),
                       "created": time.time()}, f)
            f.flush()
            os.fsync(f.fileno())
        fsio.fsync_dir(tmp)
        return tmp

    def _commit_segment(self, pdir: str, tmp: str, name: str) -> None:
        """Rename a staged temp dir to its final segment name — run
        under the store lock, right before the manifest write that
        makes it visible. Rename durability (reporter-lint
        DUR002/DUR003): every column is fsync'd at stage time, the
        segment dir's entries are fsync'd, THEN the rename, THEN the
        partition dir — a power loss right after the manifest lists
        this segment cannot surface empty columns. The content fsyncs
        live in _stage_segment (DUR002 is function-granular by design;
        the split exists so the fsync-heavy staging runs unlocked).

        A pre-existing dir at the target name is a crashed commit's
        orphan, never live data, PROVIDED we verifiably hold the lease
        at this instant: committed names are seq-monotonic (every
        commit uses manifest seq + 1 under the lease + lock), so a
        manifest can only list names at or below its seq — the name
        being committed now is above it. A holder SIGKILLed between
        rename and manifest write (chaos lease_kill) leaves exactly
        this orphan, and the next holder's commit at the same seq must
        replace it, not ENOTEMPTY. The proviso is re-checked HERE, at
        the last moment before the destructive steps: a holder that
        stalled past its TTL inside the staged merge (GC/NFS/swap) may
        have been stolen from — its deadline lapsed, so require() hits
        the slow path, sees the live new holder, and fails LOUDLY
        (LeaseHeldElsewhere) instead of clearing that holder's
        committed segment and overwriting its manifest from a stale
        read."""
        self.lease.require()
        dest = os.path.join(pdir, name)
        if os.path.exists(dest):
            if not self.lease.enabled():
                # lease off = the proviso cannot be verified: an
                # existing dest may be ANOTHER process's live commit,
                # so keep the loud ENOTEMPTY below over any clearing
                logger.error("commit target %s already exists and the "
                             "writer lease is disabled — cannot prove "
                             "it is an orphan", dest)
            else:
                # NON-DESTRUCTIVE clearing: rename the orphan aside
                # (dot-prefixed, manifest-invisible) instead of rmtree.
                # Even in the worst post-require stall — our lease
                # lapses RIGHT HERE and the dest is actually the new
                # holder's live commit — its bytes survive for
                # recovery, and the seq fence at manifest-write time
                # (append/_compact_partition) aborts our stale commit
                # before it can tear the manifest.
                aside = os.path.join(
                    pdir, f".orphan-{os.getpid()}-{next(_STAGE_IDS)}")
                logger.warning("moving crashed-commit orphan %s aside "
                               "to %s", dest, os.path.basename(aside))
                os.replace(dest, aside)
        os.replace(tmp, dest)  # lint: ignore[DUR002]
        fsio.fsync_dir(pdir)

    def _check_seq_fence(self, pdir: str, expected_seq: int) -> None:
        """Optimistic fence re-read RIGHT BEFORE a manifest write: the
        manifest's seq must still be what this commit was computed
        from. Within one process the store lock guarantees it; across
        processes only a holder that stalled past its TTL and was
        stolen from can trip it — that stale holder must abort LOUDLY
        (its renamed segment stays behind as an ignorable orphan)
        rather than overwrite the new live holder's manifest from a
        stale read."""
        current = self._read_manifest(pdir)["seq"]
        if current != expected_seq:
            metrics.count("datastore.store.stale_commits")
            raise RuntimeError(
                f"stale commit on {pdir}: manifest seq moved "
                f"{expected_seq} -> {current} underneath this writer "
                "(lease lapsed mid-commit?); aborting before the "
                "manifest tears — the staged segment is left as an "
                "orphan")

    def ingest(self, obs: ObservationBatch,
               max_deltas: Optional[int] = None,
               max_delta_bytes: Optional[int] = None,
               ingest_key: Optional[str] = None) -> int:
        """Aggregate + append a whole observation batch (possibly spanning
        partitions). Returns the number of valid rows ingested — rows a
        partition's ledger deduped (``ingest_key`` already committed
        there) are not counted. With compaction thresholds set, each
        partition THIS batch touched is pressure-checked right after its
        append — O(touched partitions), not a store-wide sweep (the
        worker tee runs this on every flush)."""
        rows = 0
        # the freshness hook records EVERY partition delta this batch
        # carries — committed, deduped (the overlay dedupes on the same
        # key, so it no-ops there too) or failed (in_store=False: the
        # tile is being spooled, and window=∞ must serve those rows
        # from the overlay until the dead-letter replay lands)
        fresh = self.freshness
        # the overlay dedupes (and uncommitted_deltas re-checks the
        # ledger) on the SAME epoch-qualified key append commits under
        ekey = self._epoch_key(ingest_key)
        for (level, index), delta in aggregate(obs).items():
            try:
                name = self.append(level, index, delta,
                                   ingest_key=ingest_key)
            except Exception:
                if fresh is not None:
                    fresh.record(level, index, delta, ekey,
                                 in_store=False,
                                 map_version=self.map_version)
                raise
            if fresh is not None:
                fresh.record(level, index, delta, ekey,
                             in_store=True,
                             map_version=self.map_version)
            if name is None:
                continue
            rows += delta.rows
            if max_deltas is not None or max_delta_bytes is not None:
                self._maybe_compact_partition(level, index, max_deltas,
                                              max_delta_bytes)
        return rows

    # -- read path ---------------------------------------------------------
    def load_segment(self, pdir: str, name: str) -> Optional[Delta]:
        """mmap one committed segment's columns; None if it was compacted
        away between manifest read and open."""
        sdir = os.path.join(pdir, name)
        try:
            cols = {col: np.load(os.path.join(sdir, col + ".npy"),
                                 mmap_mode="r")
                    for col, _ in _COLUMNS}
        except FileNotFoundError:
            return None
        return Delta(**cols)

    def live_segments(self, level: int, index: int) -> List[Delta]:
        """Every committed delta of one partition, mmap'd (may be empty).

        Handles come from the partition LRU when the manifest's segment
        list is unchanged; a changed manifest (append/compaction) keys
        differently and the stale entry ages out of the bound."""
        pdir = self.partition_dir(level, index)
        manifest = self._read_manifest(pdir)
        key = (pdir, tuple(manifest["segments"]))
        if self.handle_cache_size:
            with self._handle_lock:
                got = self._handles.get(key)
                if got is not None:
                    self._handles.move_to_end(key)
                    metrics.count("datastore.query.cache.hits")
                    return list(got)
            # only a live cache counts misses: a disabled cache emitting
            # a permanent 0% hit ratio reads as misconfiguration
            metrics.count("datastore.query.cache.misses")
        out = []
        for name in manifest["segments"]:
            d = self.load_segment(pdir, name)
            if d is not None:
                out.append(d)
        if self.handle_cache_size:
            with self._handle_lock:
                # drop any stale handle list of this partition (older
                # manifest) before inserting the fresh one
                for stale in [k for k in self._handles if k[0] == pdir
                              and k != key]:
                    del self._handles[stale]
                self._handles[key] = list(out)
                self._handles.move_to_end(key)
                while len(self._handles) > self.handle_cache_size:
                    self._handles.popitem(last=False)
        return out

    def resident_segments(self, level: int, index: int) -> np.ndarray:
        """Distinct segment ids with histogram cells in one partition,
        cached keyed by the manifest's segment list (append/compaction
        re-key it, exactly like the handle LRU — the manifest read IS
        the invalidation signal)."""
        pdir = self.partition_dir(level, index)
        manifest = self._read_manifest(pdir)
        key = (pdir, tuple(manifest["segments"]))
        if self.handle_cache_size:
            with self._handle_lock:
                got = self._resident_ids.get(key)
                if got is not None:
                    self._resident_ids.move_to_end(key)
                    return got
        from .schema import CELLS_PER_SEGMENT
        segs = [np.unique(np.asarray(part.hist_key) // CELLS_PER_SEGMENT)
                for part in self.live_segments(level, index)]
        ids = np.unique(np.concatenate(segs)) if segs \
            else np.zeros(0, dtype=np.int64)
        if self.handle_cache_size:
            with self._handle_lock:
                for stale in [k for k in self._resident_ids
                              if k[0] == pdir and k != key]:
                    del self._resident_ids[stale]
                self._resident_ids[key] = ids
                self._resident_ids.move_to_end(key)
                while len(self._resident_ids) > self.handle_cache_size:
                    self._resident_ids.popitem(last=False)
        return ids

    # -- compaction --------------------------------------------------------
    def _delta_pressure(self, pdir: str, names: List[str]) -> Tuple[int, int]:
        """(count, bytes) of uncompacted ``delta-`` segments — the inputs
        to the automatic compaction policy (a ``base-`` segment is
        already compacted and exerts no pressure)."""
        n = 0
        total = 0
        for name in names:
            if not name.startswith("delta-"):
                continue
            n += 1
            sdir = os.path.join(pdir, name)
            try:
                total += sum(os.path.getsize(os.path.join(sdir, f))
                             for f in os.listdir(sdir))
            except FileNotFoundError:
                continue
        return n, total

    def compact(self, level: Optional[int] = None,
                index: Optional[int] = None,
                max_deltas: Optional[int] = None,
                max_delta_bytes: Optional[int] = None) -> dict:
        """Merge each selected partition's segments into one ``base-``
        segment. With ``max_deltas`` / ``max_delta_bytes`` set this is
        the *automatic policy*: only partitions whose uncompacted delta
        count or byte total exceeds a threshold are compacted (the
        worker's datastore tee and the CLI pass these, so steady-state
        operation needs no manual compaction pass). Returns
        ``{"partitions", "merged_segments", "skipped"}``."""
        merged = parts = skipped = 0
        # fail fast before the partition walk; _compact_partition
        # re-checks per partition (the lease can be stolen mid-sweep)
        self.lease.require()
        thresholds = max_deltas is not None or max_delta_bytes is not None
        with metrics.timer("datastore.store.compact"):
            for lvl, idx in list(self.partitions()):
                if level is not None and lvl != level:
                    continue
                if index is not None and idx != index:
                    continue
                if thresholds:
                    got = self._maybe_compact_partition(
                        lvl, idx, max_deltas, max_delta_bytes)
                    if got is None:
                        skipped += 1
                    else:
                        merged += got
                        parts += 1
                    continue
                merged += self._compact_partition(lvl, idx)
                parts += 1
        return {"partitions": parts, "merged_segments": merged,
                "skipped": skipped}

    def _maybe_compact_partition(self, level: int, index: int,
                                 max_deltas: Optional[int],
                                 max_delta_bytes: Optional[int]
                                 ) -> Optional[int]:
        """Compact ONE partition iff its uncompacted-delta pressure
        crosses a threshold; None when below pressure (skipped)."""
        pdir = self.partition_dir(level, index)
        names = self._read_manifest(pdir)["segments"]
        n, nbytes = self._delta_pressure(pdir, names)
        if not pressure_exceeded(n, nbytes, max_deltas, max_delta_bytes):
            return None
        metrics.count("datastore.store.auto_compactions")
        return self._compact_partition(level, index)

    def _compact_partition(self, level: int, index: int) -> int:
        # same cross-process gate as append: the torn-manifest scenario
        # the lease exists for IS two compactions interleaving their
        # seq bumps (tests/test_serving_tier.py pins it)
        self.lease.require()
        with self._lock:
            pdir = self.partition_dir(level, index)
            manifest = self._read_manifest(pdir)
            names = manifest["segments"]
            if len(names) <= 1:
                return 0
            # compaction is epoch-aware: segments group by the map
            # build that produced them (untagged legacy segments form
            # their own group) and each group merges into its OWN base
            # — merging across epochs would manufacture exactly the
            # mixed-version histogram cells the epoch stamps exist to
            # prevent. The common single-epoch partition still ends in
            # one base, byte-identical to the pre-epoch behaviour.
            tags = manifest.get("epochs", {})
            groups: "OrderedDict[Optional[str], List[str]]" = OrderedDict()
            for n in names:
                groups.setdefault(tags.get(n), []).append(n)
            seq0 = manifest["seq"]
            bumps = 0
            new_segments: List[str] = []
            new_epochs: Dict[str, str] = {}
            for tag, group in groups.items():
                deltas = [d for d
                          in (self.load_segment(pdir, n) for n in group)
                          if d is not None]
                bumps += 1
                base = f"base-{seq0 + bumps:06d}"
                # staged under the lock, unlike append: the merge input
                # is the live segment list, which must not move
                # underneath it
                tmp = self._stage_segment(pdir, merge_deltas(deltas))
                self._commit_segment(pdir, tmp, base)
                new_segments.append(base)
                if tag is not None:
                    new_epochs[base] = tag
            # chaos hook (lease_kill): a crash HERE dies HOLDING the
            # lease mid-compaction, in the widest window — the merged
            # base- dir is renamed in place but the manifest still
            # lists the old segments. Readers stay manifest-driven (the
            # orphan dir is invisible), and the next process must steal
            # the dead holder's lease and re-compact to an untorn state
            faults.failpoint("datastore.compact")
            self._check_seq_fence(pdir, seq0)
            # the ingested ledger survives compaction: the merged base
            # still CONTAINS those flushes, so dropping their keys would
            # re-open the double-ingest window the ledger closes
            compacted = {"seq": seq0 + bumps, "segments": new_segments}
            if new_epochs:
                compacted["epochs"] = new_epochs
            if manifest.get("map_version"):
                compacted["map_version"] = manifest["map_version"]
            if manifest.get("ingested"):
                compacted["ingested"] = manifest["ingested"]
            self._write_manifest(pdir, compacted)
            # the new manifest is durable; merged segment dirs are dead
            for name in names:
                shutil.rmtree(os.path.join(pdir, name), ignore_errors=True)
            # garbage-collect aside-renamed orphans while we verifiably
            # hold the lease: they are manifest-invisible, so this is
            # pure disk hygiene. (.tmp- stage dirs are NOT touched — a
            # concurrent append in THIS process stages unlocked, so an
            # in-flight .tmp- dir may be live)
            for leftover in os.listdir(pdir):
                if leftover.startswith(".orphan-"):
                    shutil.rmtree(os.path.join(pdir, leftover),
                                  ignore_errors=True)
            logger.info("compacted %d/%d: %d segments -> %s",
                        level, index, len(names),
                        ",".join(new_segments))
            return len(names)

    # -- introspection -----------------------------------------------------
    def merged_cells(self) -> Dict[tuple, tuple]:
        """``{(level, index, hist_key): (count, speed_sum)}`` merged
        across every committed segment (speed sums rounded to 1e-6) —
        the layout-independent parity comparand the chaos/bigreplay
        exactly-once proofs assert with: two stores that compacted at
        different points differ byte-wise but must carry identical
        cells. ONE definition, so the harnesses cannot drift apart."""
        out: Dict[tuple, tuple] = {}
        for level, index in self.partitions():
            parts = self.live_segments(level, index)
            if not parts:
                continue
            merged = merge_deltas(parts)
            keys = np.asarray(merged.hist_key)
            counts = np.asarray(merged.hist_count)
            sums = np.asarray(merged.hist_speed_sum)
            for k, c, s in zip(keys.tolist(), counts.tolist(),
                               sums.tolist()):
                out[(level, index, k)] = (c, round(s, 6))
        return out

    def stats(self) -> dict:
        """Partition/segment/cell totals plus on-disk byte size."""
        out: Dict[str, int] = {"partitions": 0, "segments": 0, "cells": 0,
                               "rows": 0, "transitions": 0, "bytes": 0}
        for level, index in self.partitions():
            out["partitions"] += 1
            pdir = self.partition_dir(level, index)
            for name in self._read_manifest(pdir)["segments"]:
                sdir = os.path.join(pdir, name)
                try:
                    with open(os.path.join(sdir, "meta.json"),
                              encoding="utf-8") as f:
                        meta = json.load(f)
                except (FileNotFoundError, ValueError):
                    continue
                out["segments"] += 1
                out["cells"] += meta.get("cells", 0)
                out["rows"] += meta.get("rows", 0)
                out["transitions"] += meta.get("transitions", 0)
                out["bytes"] += sum(
                    os.path.getsize(os.path.join(sdir, f))
                    for f in os.listdir(sdir))
        return out


class EpochView:
    """Store-protocol facade pinning reads to ONE map_version.

    Satisfies the same three-method protocol the query layer sweeps
    (``partitions`` / ``live_segments`` / ``resident_segments``, like
    freshness.OverlayView), serving only segments whose manifest epoch
    tag matches the pin. Untagged segments — everything committed
    before the store carried a version — pass through, so enabling
    versioning on an existing store never hides its history. Reads are
    manifest-driven per call and bypass the handle LRU: a pinned query
    is the rare post-swap audit path, not the dashboard hot path.
    """

    def __init__(self, store: HistogramStore, map_version: str):
        self.store = store
        self.map_version = str(map_version)

    def partitions(self):
        return self.store.partitions()

    def live_segments(self, level: int, index: int) -> List[Delta]:
        pdir = self.store.partition_dir(level, index)
        manifest = self.store._read_manifest(pdir)
        tags = manifest.get("epochs", {})
        out = []
        for name in manifest["segments"]:
            tag = tags.get(name)
            if tag is not None and tag != self.map_version:
                continue
            d = self.store.load_segment(pdir, name)
            if d is not None:
                out.append(d)
        return out

    def resident_segments(self, level: int, index: int) -> np.ndarray:
        from .schema import CELLS_PER_SEGMENT
        segs = [np.unique(np.asarray(p.hist_key) // CELLS_PER_SEGMENT)
                for p in self.live_segments(level, index)]
        return np.unique(np.concatenate(segs)) if segs \
            else np.zeros(0, dtype=np.int64)


__all__ = ["HistogramStore", "EpochView", "MANIFEST"]
