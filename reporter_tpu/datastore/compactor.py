"""Background compaction: the delta-pressure policy off the flush path.

PR 4's automatic policy ran ``_maybe_compact_partition`` INLINE on the
worker tee's ingest — every flush that tipped a partition over pressure
paid the whole merge (hundreds of ms of staged fsyncs) inside the flush
hot path, and the pre-fork service never compacted at all. This thread
is the fix: a paced loop, owned by WHICHEVER process holds the writer
lease (:mod:`lease`), that sweeps the store for partitions over the
same ``max_deltas`` / ``max_delta_bytes`` thresholds and compacts them
out of band. A process that does not hold the lease skips its pass
(counted) instead of contending — exactly one compactor is ever live
per store root.

The sweep also maintains the delta-pressure BACKLOG gauge
(``pending()``): how many partitions sit over pressure and how many
uncompacted delta segments/bytes they carry — surfaced on ``/health``
and the worker heartbeat, so "compaction is falling behind" is a gauge
long before it is a slow query.

``REPORTER_TPU_COMPACT_INTERVAL_S`` paces the loop (default 5 s;
``0`` disables — callers then keep whatever inline policy they had).
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from ..utils import metrics
from .lease import LeaseHeldElsewhere

logger = logging.getLogger("reporter_tpu.datastore")


def compact_interval_s() -> float:
    from ..utils.runtime import _env_float
    return _env_float("REPORTER_TPU_COMPACT_INTERVAL_S", 5.0)


class BackgroundCompactor:
    """Paced compaction thread over one store (see module docstring)."""

    def __init__(self, store, max_deltas: Optional[int] = None,
                 max_delta_bytes: Optional[int] = None,
                 interval_s: Optional[float] = None):
        self.store = store
        self.max_deltas = max_deltas
        self.max_delta_bytes = max_delta_bytes
        self.interval_s = interval_s if interval_s is not None \
            else compact_interval_s()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # last completed sweep's backlog gauge (plain dict swap under
        # the GIL: readers get the old or the new snapshot, never a
        # mix) + the flagged partition list that sweep found
        self._backlog = {"partitions_over": 0, "delta_segments": 0,
                         "delta_bytes": 0}
        self._over: list = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "BackgroundCompactor":
        if self._thread is None and self.interval_s > 0:
            # a stop()ed compactor must be restartable: a set event
            # would make the fresh thread's first wait() return
            # immediately and die silently
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="datastore-compactor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Signal + JOIN (the worker drain ordering contract: no thread
        may outlive the store handles its owner is about to drop)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as e:  # keep pacing through transient I/O
                logger.error("compactor pass failed (will retry): %s", e)

    # -- one pass ----------------------------------------------------------
    def run_once(self) -> dict:
        """One sweep: refresh the backlog gauge, then compact exactly
        the partitions that sweep flagged — IF this process holds the
        lease. The gauge's flagged list DRIVES the compaction (no
        second whole-store walk); ``_maybe_compact_partition``
        re-checks each flagged partition's pressure at compaction
        time, through the same shared predicate, so a partition
        another process compacted meanwhile is skipped, not
        re-merged."""
        metrics.count("datastore.compactor.passes")
        # freshness maintenance rides the paced pass, BEFORE the
        # lease-gated compaction (both are read-only — every process
        # runs them, leased or not): viewport materialisations refresh
        # off the hot path, and the feed's store watcher publishes
        # tile events for commits other processes made
        fresh = getattr(self.store, "freshness", None)
        if fresh is not None:
            try:
                fresh.on_compactor_pass()
            except Exception as e:
                logger.error("freshness pass failed (will retry): %s", e)
        backlog = self.pending(refresh=True)
        if not backlog["partitions_over"]:
            return {"compacted": 0, "backlog": backlog}
        if not self.store.lease.acquire():
            # another process owns the store right now; it runs the
            # compactor, we keep gauging
            metrics.count("datastore.compactor.unleased")
            return {"compacted": 0, "backlog": backlog, "unleased": True}
        compacted = 0
        try:
            for level, index in self._over:
                if self.store._maybe_compact_partition(
                        level, index, self.max_deltas,
                        self.max_delta_bytes) is not None:
                    compacted += 1
            if compacted:
                metrics.count("datastore.compactor.compacted", compacted)
        except LeaseHeldElsewhere:
            # stolen between acquire and commit (expiry under load):
            # drop the pass, the new holder's compactor takes over
            metrics.count("datastore.compactor.unleased")
        self.pending(refresh=True)
        return {"compacted": compacted, "backlog": self._backlog}

    # -- backlog gauge -----------------------------------------------------
    def pending(self, refresh: bool = False) -> dict:
        """{"partitions_over", "delta_segments", "delta_bytes"} of the
        last sweep (cached — /health and heartbeats must never pay a
        store walk); ``refresh=True`` recomputes (the paced loop) and
        records the flagged partition list run_once compacts from."""
        if refresh:
            from .store import pressure_exceeded
            over: list = []
            segs = nbytes = 0
            for level, index in list(self.store.partitions()):
                pdir = self.store.partition_dir(level, index)
                names = self.store._read_manifest(pdir)["segments"]
                n, total = self.store._delta_pressure(pdir, names)
                if pressure_exceeded(n, total, self.max_deltas,
                                     self.max_delta_bytes):
                    over.append((level, index))
                    segs += n
                    nbytes += total
            self._over = over
            self._backlog = {"partitions_over": len(over),
                             "delta_segments": segs,
                             "delta_bytes": nbytes}
        return dict(self._backlog)


__all__ = ["BackgroundCompactor", "compact_interval_s"]
