"""Datastore value layout: speed-histogram axes, composite keys, and the
columnar observation batch.

The datastore aggregates tile observations (one CSV row / one ``Segment``
each) into per-segment speed histograms over two fixed axes:

- **hour-of-week**: 168 buckets, Monday 00:00 UTC = 0 (the serving
  granularity of the reference ecosystem's datastore — traffic is
  periodic by week, so a week of hours is the smallest cycle that keeps
  rush hours apart without storing raw timestamps).
- **speed bin**: ``SPEED_BIN_KPH``-wide bins from 0 to ``SPEED_MAX_KPH``
  plus one overflow bin (``N_SPEED_BINS`` total). Bin ``i`` covers
  ``[i*SPEED_BIN_KPH, (i+1)*SPEED_BIN_KPH)``.

A histogram cell is addressed by one int64 composite key::

    key = segment_id * CELLS_PER_SEGMENT + hour_of_week * N_SPEED_BINS + bin

``segment_id`` is a 46-bit OSMLR id and ``CELLS_PER_SEGMENT`` is
168 * 25 = 4200 < 2**13, so the product stays below 2**59 — comfortably
inside int64. Composite keys sort by (segment, hour, bin), which is what
makes per-segment query a binary-searched contiguous slice of every
sorted partition file (store.py).

Transitions (segment -> next segment counts) keep two id columns; two
46-bit ids cannot share an int64.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core.osmlr import (
    INVALID_SEGMENT_ID,
    LEVEL_BITS,
    TILE_INDEX_BITS,
)

HOURS_PER_WEEK = 168

SPEED_BIN_KPH = 5.0
SPEED_MAX_KPH = 120.0
#: 24 regular bins + 1 overflow for speeds >= SPEED_MAX_KPH
N_SPEED_BINS = int(SPEED_MAX_KPH / SPEED_BIN_KPH) + 1

CELLS_PER_SEGMENT = HOURS_PER_WEEK * N_SPEED_BINS

#: upper edges of the regular bins — searchsorted target (overflow bin is
#: everything at or past the last edge)
SPEED_BIN_EDGES_KPH = np.arange(
    SPEED_BIN_KPH, SPEED_MAX_KPH + SPEED_BIN_KPH / 2, SPEED_BIN_KPH)

#: 25-bit (level | tile index) mask — the partition key lives in the low
#: bits of every segment id (core/osmlr.py)
GRAPH_TILE_MASK = (1 << (LEVEL_BITS + TILE_INDEX_BITS)) - 1

#: epoch 0 is Thursday; shift so hour-of-week 0 is Monday 00:00 UTC
_EPOCH_DOW_OFFSET_H = 3 * 24


def hour_of_week(epoch_s: np.ndarray) -> np.ndarray:
    """Vectorised epoch seconds -> hour-of-week (0..167, Monday 00:00=0)."""
    return ((np.asarray(epoch_s, dtype=np.int64) // 3600
             + _EPOCH_DOW_OFFSET_H) % HOURS_PER_WEEK).astype(np.int64)


def speed_bin(speed_kph: np.ndarray) -> np.ndarray:
    """Vectorised speed -> bin index (last bin catches the overflow)."""
    return np.minimum(
        np.searchsorted(SPEED_BIN_EDGES_KPH, speed_kph, side="right"),
        N_SPEED_BINS - 1).astype(np.int64)


def hist_key(segment_id: np.ndarray, hour: np.ndarray,
             sbin: np.ndarray) -> np.ndarray:
    return (np.asarray(segment_id, dtype=np.int64) * CELLS_PER_SEGMENT
            + np.asarray(hour, dtype=np.int64) * N_SPEED_BINS
            + np.asarray(sbin, dtype=np.int64))


def split_hist_key(key: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Composite key -> (segment_id, hour_of_week, speed_bin) columns."""
    key = np.asarray(key, dtype=np.int64)
    seg, cell = np.divmod(key, CELLS_PER_SEGMENT)
    hour, sbin = np.divmod(cell, N_SPEED_BINS)
    return seg, hour, sbin


def segment_key_range(segment_id: int) -> Tuple[int, int]:
    """Half-open composite-key range covering one segment's cells."""
    lo = int(segment_id) * CELLS_PER_SEGMENT
    return lo, lo + CELLS_PER_SEGMENT


def bin_centers_kph() -> np.ndarray:
    """Representative speed per bin (overflow pinned to its lower edge)."""
    centers = (np.arange(N_SPEED_BINS, dtype=np.float64) + 0.5) * SPEED_BIN_KPH
    centers[-1] = SPEED_MAX_KPH
    return centers


@dataclass
class ObservationBatch:
    """Columnar tile observations — the datastore's zero-dict wire format.

    One element per tile CSV row / ``Segment`` observation. All arrays
    share length; ``next_id`` uses ``INVALID_SEGMENT_ID`` for "no next
    segment" exactly like the 40-byte binary layout.
    """

    segment_id: np.ndarray   # int64
    next_id: np.ndarray      # int64
    duration_s: np.ndarray   # float64 (CSV carries round(max-min) seconds)
    count: np.ndarray        # int64 (tile CSV count column; 1 per raw row)
    length_m: np.ndarray     # int64
    queue_m: np.ndarray      # int64
    min_ts: np.ndarray       # int64 epoch seconds
    max_ts: np.ndarray       # int64 epoch seconds

    def __len__(self) -> int:
        return int(self.segment_id.shape[0])

    @classmethod
    def empty(cls) -> "ObservationBatch":
        z64 = np.zeros(0, dtype=np.int64)
        return cls(z64, z64.copy(), np.zeros(0, dtype=np.float64),
                   z64.copy(), z64.copy(), z64.copy(), z64.copy(),
                   z64.copy())

    @classmethod
    def from_segments(cls, segments: List) -> "ObservationBatch":
        """Columnarise ``core.types.Segment`` structs — the worker's
        in-process flush path, no CSV in between (one bulk pass)."""
        n = len(segments)
        if n == 0:
            return cls.empty()
        seg = np.fromiter((s.id for s in segments), dtype=np.int64, count=n)
        nxt = np.fromiter((s.next_id for s in segments), dtype=np.int64,
                          count=n)
        mn = np.fromiter((s.min for s in segments), dtype=np.float64, count=n)
        mx = np.fromiter((s.max for s in segments), dtype=np.float64, count=n)
        ln = np.fromiter((s.length for s in segments), dtype=np.int64,
                         count=n)
        qu = np.fromiter((s.queue for s in segments), dtype=np.int64, count=n)
        # same duration quantisation as Segment.csv_row (Java half-up
        # rounding), so the in-process path and the CSV path aggregate
        # identically
        dur = np.floor((mx - mn) + 0.5)
        return cls(seg, nxt, dur, np.ones(n, dtype=np.int64), ln, qu,
                   np.floor(mn).astype(np.int64),
                   np.ceil(mx).astype(np.int64))

    def speeds_kph(self) -> np.ndarray:
        """Harmonic-consistent per-observation speed: length/duration.
        Zero-duration observations yield inf and are dropped by the
        aggregator's validity mask."""
        with np.errstate(divide="ignore"):
            return np.where(self.duration_s > 0,
                            self.length_m / np.maximum(self.duration_s, 1e-9),
                            np.inf) * 3.6

    def valid_mask(self) -> np.ndarray:
        """Observations the aggregator accepts: positive duration and
        length, non-negative queue (Segment.valid semantics, columnar)."""
        return ((self.duration_s > 0) & (self.length_m > 0)
                & (self.queue_m >= 0) & (self.min_ts > 0)
                & (self.max_ts > 0))


__all__ = [
    "HOURS_PER_WEEK", "SPEED_BIN_KPH", "SPEED_MAX_KPH", "N_SPEED_BINS",
    "CELLS_PER_SEGMENT", "SPEED_BIN_EDGES_KPH", "GRAPH_TILE_MASK",
    "INVALID_SEGMENT_ID", "hour_of_week", "speed_bin", "hist_key",
    "split_hist_key", "segment_key_range", "bin_centers_kph",
    "ObservationBatch",
]
