"""Cross-process writer lease over one store root.

PR 11's pre-fork serving and PR 9's dead-letter drainer made it normal
for SEVERAL processes to point at one store directory, but the commit
protocol in :mod:`store` was only serialised by an in-process
``threading.Lock``: two processes compacting the same partition each
pass their own lock, interleave ``seq`` bumps and manifest rewrites,
and one writer's committed segment vanishes from the manifest the other
writes last (the torn-manifest regression test in
``tests/test_serving_tier.py`` pins the exact interleaving). This
module is the fix: a single ``.lease`` file in the store root that
every mutating entry point (``append``/``ingest_*``/``compact``) must
hold.

Protocol — deadline lease, flock-guarded critical section:

- The lease STATE is the file's JSON body ``{"pid", "deadline"}``
  (wall-clock epoch seconds; monotonic clocks are not comparable
  across processes). Holding the lease means *your pid is in the file
  and the deadline has not passed*.
- Every read-modify-write of that state runs under an exclusive
  ``fcntl.flock`` on the file — the flock serialises acquire attempts,
  so two stealers can never both write themselves in; it is NOT held
  between mutations (a SIGKILL'd holder would otherwise pin it until
  fd close anyway, but the deadline must also bound a *stuck* live
  holder).
- A non-holder may STEAL when the recorded deadline has expired or the
  recorded pid no longer exists (``os.kill(pid, 0)``) — the chaos
  ``lease_kill`` scenario SIGKILLs the holder mid-compaction and
  asserts the next process takes over cleanly.
- The holder refreshes its deadline lazily: a mutation only rewrites
  the file when less than half the TTL remains, so an append-heavy
  tee pays one flock'd write per ``ttl/2``, not per flush.

``REPORTER_TPU_STORE_LEASE_S`` is the TTL (default 30 s; ``0``
disables the lease entirely — every acquire succeeds without touching
disk, the single-process test/CLI fast path). Counters surface as
``datastore.lease.*`` and the holder state on ``/health``.

The lease file is coordination state, not data: a torn body parses as
"no holder" and the flock around every read/write keeps that from ever
granting two live writers at once — so it deliberately skips the fsio
durability protocol (and lives outside the DUR-checked modules).
"""
from __future__ import annotations

import errno
import json
import logging
import os
import time
from typing import Optional

from ..utils import faults, metrics
from ..utils import locks as _locks

try:  # pragma: no cover - fcntl is always present on the Linux targets
    import fcntl
except ImportError:  # non-POSIX fallback: flock degrades to a no-op
    fcntl = None

logger = logging.getLogger("reporter_tpu.datastore")

LEASE_NAME = ".lease"


def lease_ttl_s() -> float:
    from ..utils.runtime import _env_float
    return _env_float("REPORTER_TPU_STORE_LEASE_S", 30.0)


class LeaseHeldElsewhere(RuntimeError):
    """A mutating store call was refused: another live process holds the
    writer lease. The worker tee catches this like any tee failure and
    spools the tile body to the dead-letter layout (replayable once the
    lease frees up); ``ingest_dir`` aborts WITHOUT quarantining."""


class StoreLease:
    """The writer lease of one store root (see module docstring).

    One instance per :class:`~reporter_tpu.datastore.store.HistogramStore`;
    holder identity is the PROCESS (pid), so several store objects in
    one process share holdership — exactly the scope the old in-process
    lock pretended to cover. ``owner_pid`` is overridable so tests can
    impersonate a foreign live process without forking.
    """

    def __init__(self, root: str, ttl_s: Optional[float] = None):
        self.root = root
        self.path = os.path.join(root, LEASE_NAME)
        self._ttl = ttl_s
        #: None = this process (``os.getpid()`` read at use time, so a
        #: forked child automatically identifies as itself); tests set
        #: a foreign live pid to simulate another process's holdership
        #: without forking
        self.owner_pid: Optional[int] = None
        # local belief: the wall-clock deadline we last wrote for
        # ourselves (0 = not holding) and the identity that wrote it.
        # Guarded by _lock; a belief written under another identity
        # (pre-fork parent) is discarded, never inherited.
        self._deadline = 0.0
        self._belief_pid = 0
        self._lock = _locks.new_lock("datastore.lease")

    def _me(self) -> int:
        return self.owner_pid if self.owner_pid is not None \
            else os.getpid()

    @property
    def ttl_s(self) -> float:
        return self._ttl if self._ttl is not None else lease_ttl_s()

    def enabled(self) -> bool:
        return self.ttl_s > 0

    # -- acquisition -------------------------------------------------------
    def acquire(self) -> bool:
        """Take or refresh the lease; False when a live, unexpired
        foreign holder has it. Fast path: while more than half our TTL
        remains, no disk is touched."""
        ttl = self.ttl_s
        if ttl <= 0:
            return True
        with self._lock:
            if self._belief_pid != self._me():
                # forked child (or re-identified test lease): the
                # recorded holdership belief is not ours
                self._deadline = 0.0
            now = time.time()
            if self._deadline - now > ttl / 2.0:
                return True
            return self._acquire_slow(now, ttl)

    def require(self) -> None:
        """``acquire`` or raise :class:`LeaseHeldElsewhere`."""
        if not self.acquire():
            metrics.count("datastore.lease.rejected")
            raise LeaseHeldElsewhere(
                f"writer lease on {self.root} held by another process "
                f"(see {self.path}); spool or retry after expiry")

    def _acquire_slow(self, now: float, ttl: float) -> bool:
        """Flock'd read-modify-write of the lease file; _lock held."""
        # failure domain: an injected lease fault (chaos) or a real I/O
        # error on the lease file refuses the mutation — callers spool/
        # retry, they never tear a manifest on an unknown lease state
        faults.failpoint("datastore.lease")
        me = self._me()
        os.makedirs(self.root, exist_ok=True)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_EX)
            state = self._read_state(fd)
            holder = state.get("pid")
            deadline = float(state.get("deadline") or 0.0)
            if holder is not None and holder != me \
                    and deadline > now and _pid_alive(holder):
                self._deadline = 0.0
                return False
            if holder is None or holder == me:
                if self._deadline <= 0.0:
                    metrics.count("datastore.lease.acquires")
                else:
                    metrics.count("datastore.lease.renewals")
            else:
                # foreign holder, but expired or dead: take over
                metrics.count("datastore.lease.steals")
                if deadline <= now:
                    metrics.count("datastore.lease.expired")
                logger.warning(
                    "stealing writer lease on %s from pid %s (%s)",
                    self.root, holder,
                    "expired" if deadline <= now else "dead")
            self._deadline = now + ttl
            self._belief_pid = me
            self._write_state(fd, {"pid": me,
                                   "deadline": self._deadline})
            return True
        finally:
            os.close(fd)  # releases the flock

    def release(self) -> None:
        """Give the lease up (clean shutdown); no-op when not held."""
        if self.ttl_s <= 0:
            return
        with self._lock:
            if self._deadline <= 0.0:
                return
            self._deadline = 0.0
            try:
                fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
            except OSError:
                return
            try:
                if fcntl is not None:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                if self._read_state(fd).get("pid") == self._me():
                    self._write_state(fd, {})
                metrics.count("datastore.lease.releases")
            finally:
                os.close(fd)

    # -- introspection -----------------------------------------------------
    def held(self) -> bool:
        """Do WE currently believe we hold an unexpired lease (no disk
        I/O — the /health gauge, not an acquisition)."""
        if self.ttl_s <= 0:
            return True
        with self._lock:
            return self._belief_pid == self._me() \
                and self._deadline > time.time()

    def snapshot(self) -> dict:
        """Holder view for /health: who the FILE says holds it, plus
        whether this process is that holder."""
        ttl = self.ttl_s
        if ttl <= 0:
            return {"enabled": False}
        state = {}
        try:
            with open(self.path, encoding="utf-8") as f:
                state = json.loads(f.read() or "{}")
        except (OSError, ValueError):
            pass
        deadline = float(state.get("deadline") or 0.0)
        return {"enabled": True, "ttl_s": ttl,
                "holder_pid": state.get("pid"),
                "expires_in_s": round(deadline - time.time(), 3)
                if deadline else None,
                "held_by_us": self.held()}

    # -- file body ---------------------------------------------------------
    @staticmethod
    def _read_state(fd: int) -> dict:
        try:
            os.lseek(fd, 0, os.SEEK_SET)
            raw = os.read(fd, 4096)
            got = json.loads(raw.decode("utf-8") or "{}")
            return got if isinstance(got, dict) else {}
        except (OSError, ValueError):
            # a torn body is "no holder": safe, because every writer of
            # this file sits behind the same flock we hold right now
            return {}

    @staticmethod
    def _write_state(fd: int, state: dict) -> None:
        body = json.dumps(state).encode("utf-8")
        os.lseek(fd, 0, os.SEEK_SET)
        os.truncate(fd, 0)
        os.write(fd, body)


def _pid_alive(pid) -> bool:
    try:
        os.kill(int(pid), 0)
    except (ProcessLookupError, ValueError):
        return False
    except OSError as e:  # EPERM: alive, owned by someone else
        return e.errno == errno.EPERM
    return True


__all__ = ["StoreLease", "LeaseHeldElsewhere", "LEASE_NAME",
           "lease_ttl_s"]
