"""Whole-batch histogram aggregation — the datastore's hot kernel.

Turns a columnar :class:`~reporter_tpu.datastore.schema.ObservationBatch`
into per-partition deltas:

- **histogram cells**: sorted unique composite keys (segment x
  hour-of-week x speed bin, schema.hist_key) with per-cell observation
  counts and speed sums (the speed sum keeps query-side means exact
  instead of bin-center approximations),
- **transition counts**: sorted unique (segment, next segment) pairs.

The whole batch flows through ``np.searchsorted`` / ``np.unique`` /
``np.add.at`` — no per-row Python. This module is declared in the lint
hot set (analysis/hotpath.py) alongside the matcher pipeline: the same
HP001-003 purity rules that keep host prep columnar keep this kernel
columnar.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from ..utils import metrics
from .schema import (
    GRAPH_TILE_MASK,
    INVALID_SEGMENT_ID,
    ObservationBatch,
    hist_key,
    hour_of_week,
    speed_bin,
)
from ..core.osmlr import LEVEL_BITS, LEVEL_MASK


@dataclass
class Delta:
    """One partition's aggregation increment (all arrays sorted by key)."""

    hist_key: np.ndarray        # int64, sorted unique composite keys
    hist_count: np.ndarray      # int64 observations per cell
    hist_speed_sum: np.ndarray  # float64 sum of kph per cell
    trans_from: np.ndarray      # int64, sorted (from, to) pairs
    trans_to: np.ndarray        # int64
    trans_count: np.ndarray     # int64

    def __len__(self) -> int:
        return int(self.hist_key.shape[0])

    @property
    def rows(self) -> int:
        return int(self.hist_count.sum()) if len(self) else 0


def _reduce_hist(keys: np.ndarray, counts: np.ndarray,
                 speed_mass: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                                  np.ndarray]:
    """Sum counts and speed mass (sum of kph) over duplicate keys."""
    ukeys, inv = np.unique(keys, return_inverse=True)
    csum = np.zeros(ukeys.shape[0], dtype=np.int64)
    ssum = np.zeros(ukeys.shape[0], dtype=np.float64)
    np.add.at(csum, inv, counts)
    np.add.at(ssum, inv, speed_mass)
    return ukeys, csum, ssum


def _reduce_trans(frm: np.ndarray, to: np.ndarray,
                  counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                               np.ndarray]:
    """Sum counts over duplicate (from, to) pairs, sorted by (from, to)."""
    pairs = np.stack([frm, to], axis=1)
    upairs, inv = np.unique(pairs, axis=0, return_inverse=True)
    csum = np.zeros(upairs.shape[0], dtype=np.int64)
    np.add.at(csum, inv, counts)
    return upairs[:, 0].copy(), upairs[:, 1].copy(), csum


def aggregate(obs: ObservationBatch) -> Dict[Tuple[int, int], Delta]:
    """Aggregate a batch into ``{(level, tile_index): Delta}``.

    Invalid observations (zero duration/length, unset timestamps) are
    masked out in one pass; transitions additionally require a real next
    segment id.
    """
    with metrics.timer("datastore.aggregate"):
        keep = obs.valid_mask()
        seg = obs.segment_id[keep]
        if seg.shape[0] == 0:
            return {}
        nxt = obs.next_id[keep]
        cnt = obs.count[keep]
        kph = obs.speeds_kph()[keep]
        hour = hour_of_week(obs.min_ts[keep])
        keys = hist_key(seg, hour, speed_bin(kph))

        tile_part = seg & GRAPH_TILE_MASK
        out: Dict[Tuple[int, int], Delta] = {}
        # few distinct graph tiles per flush — the per-partition loop is
        # coarse; everything inside it is whole-array numpy
        for tile in np.unique(tile_part):
            m = tile_part == tile
            hk, hc, hs = _reduce_hist(keys[m], cnt[m], kph[m] * cnt[m])
            mt = m & (nxt != INVALID_SEGMENT_ID)
            tf, tt, tc = _reduce_trans(seg[mt], nxt[mt], cnt[mt])
            level = int(tile) & LEVEL_MASK
            index = int(tile) >> LEVEL_BITS
            out[(level, index)] = Delta(hk, hc, hs, tf, tt, tc)
        metrics.count("datastore.aggregate.rows", int(seg.shape[0]))
        return out


def merge_deltas(parts) -> Delta:
    """Merge already-reduced deltas of ONE partition into one Delta —
    the compaction kernel (store.py) and the multi-file query reducer."""
    parts = [p for p in parts if len(p) or p.trans_from.shape[0]]
    if not parts:
        z = np.zeros(0, dtype=np.int64)
        return Delta(z, z.copy(), np.zeros(0, dtype=np.float64),
                     z.copy(), z.copy(), z.copy())
    hk, hc, hs = _reduce_hist(
        np.concatenate([p.hist_key for p in parts]),
        np.concatenate([p.hist_count for p in parts]),
        np.concatenate([p.hist_speed_sum for p in parts]))
    tf, tt, tc = _reduce_trans(
        np.concatenate([p.trans_from for p in parts]),
        np.concatenate([p.trans_to for p in parts]),
        np.concatenate([p.trans_count for p in parts]))
    return Delta(hk, hc, hs, tf, tt, tc)


__all__ = ["Delta", "aggregate", "merge_deltas"]
