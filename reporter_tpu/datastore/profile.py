"""Per-city route-memo profile: persist the hot state, restore it warm.

A freshly loaded city pays a cold native route-pair memo: its first
request batch runs every (edge_from, edge_to) Dijkstra from scratch —
exactly the latency spike the multi-city LRU (service/cities.py) would
otherwise inflict on every residency swap. The fix is the SSD-paper
move of persisting the cache's hot state: after a representative replay
(``datastore profile`` CLI, serve_smoke, or a live drain), the native
memo's RESIDENT pairs — clock eviction keeps them biased hot, so they
ARE the city's top route pairs — are exported
(``rt_route_memo_stats``-instrumented: the artifact records the
hit/miss counters of the replay that produced it) and committed as a
``.profile`` JSON artifact in the city's store root. Loading the city
later warms the memo from the artifact BEFORE the first request:
``rt_route_memo_warm`` recomputes each pair's node kernel with the
same bounded Dijkstra the serving path runs on a miss, so a warmed hit
is bit-identical to a cold-computed one — the pre-warm changes
latency, never answers.

The artifact is dot-named like every other control file in a durable
layout (``.lease``, ``.traces`` ...): tile walkers, spool accounting
and parity fingerprints all skip it by the dot rule.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Optional

import numpy as np

from ..utils import fsio, metrics

logger = logging.getLogger("reporter_tpu.datastore")

PROFILE_NAME = ".profile"

#: node-kernel search bound used when re-deriving warmed entries; the
#: serving path's min_bound_m floor — a kernel proven to a SMALLER
#: bound than a later query needs would re-search anyway
WARM_BOUND_M = 500.0


def profile_path(store_root: str) -> str:
    return os.path.join(store_root, PROFILE_NAME)


def export_profile(matcher, path: str, cap: int = 1 << 16,
                   city: Optional[str] = None) -> dict:
    """Dump the matcher's resident route-memo pairs to a committed
    ``.profile`` artifact (fsio atomic — a half-written profile would
    warm garbage). Returns the artifact dict; ``pairs`` is empty when
    the matcher runs the numpy fallback (no native memo to dump)."""
    pairs = []
    stats = None
    if getattr(matcher, "runtime", None) is not None:
        ea, eb = matcher.runtime.route_memo_export(cap)
        pairs = np.stack([ea, eb], axis=1).tolist() if ea.size else []
        stats = matcher.runtime.route_memo_stats()
    # frontier-bound table: the device route kernel's observed relaxation
    # depth + chunk bound over the replay (None when the kernel never
    # ran). Warming seeds the next residency's sweep cap from it, so a
    # freshly loaded city relaxes to the recorded frontier instead of
    # the worst-case node count.
    kern = getattr(matcher, "_route_kernel", None)
    route_table = kern.stats() if kern is not None else None
    art = {
        "version": 1,
        "city": city,
        "n_pairs": len(pairs),
        # the replay's memo counters: how warm the memo that produced
        # this profile actually was (an all-miss replay exports noise)
        "memo_stats": stats,
        "route_table": route_table,
        "pairs": pairs,
    }
    fsio.atomic_write_text(path, json.dumps(art, separators=(",", ":")))
    metrics.count("datastore.profile.exports")
    logger.info("exported %d route-memo pairs to %s", len(pairs), path)
    return art


def load_profile(path: str) -> Optional[dict]:
    """Parse a profile artifact; None when absent or unparseable (a
    corrupt profile costs the pre-warm, never the city load)."""
    try:
        with open(path, encoding="utf-8") as f:
            art = json.load(f)
        if not isinstance(art, dict) or art.get("version") != 1:
            raise ValueError(f"unknown profile version in {path}")
        return art
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("unreadable profile %s (skipping pre-warm): %s",
                       path, e)
        return None


def warm_matcher(matcher, profile: Optional[dict],
                 bound_m: float = WARM_BOUND_M) -> int:
    """Pre-warm a matcher's native route memo from a profile artifact;
    returns pairs warmed (0 on the numpy fallback, an empty profile, or
    a disabled memo). Out-of-range edge ids — a profile exported from a
    different graph build — are skipped inside the native call."""
    if profile is None:
        return 0
    # seed the device route kernel's sweep cap from the artifact's
    # frontier-bound table (route.device path; a malformed table costs
    # only the hint). The kernel is built here iff the knob enables it —
    # city load is exactly where the one-time build belongs.
    table = profile.get("route_table")
    if isinstance(table, dict):
        try:
            build = getattr(matcher, "_device_route_kernel", None)
            kern = build() if build is not None else None
            if kern is not None:
                kern.seed_hint(int(table.get("route_hops") or 0))
            # warmed host kernels prove themselves to the observed
            # serving bound, not just the floor — a kernel proven to a
            # smaller bound than a query needs re-searches anyway
            bound_m = max(bound_m, float(table.get("route_bound_m") or 0))
        except (TypeError, ValueError) as e:
            logger.warning("malformed profile route_table (ignored): %s", e)
    if getattr(matcher, "runtime", None) is None:
        return 0
    pairs = profile.get("pairs") or []
    if not pairs:
        return 0
    # a structurally broken artifact (ragged/non-pair rows) must cost
    # the pre-warm, never the city load — same contract as a corrupt
    # file in load_profile
    try:
        arr = np.asarray(pairs, dtype=np.int64)
        if arr.ndim != 2 or arr.shape[1] != 2:
            raise ValueError(f"pairs must be (n, 2), got {arr.shape}")
        warmed = matcher.runtime.route_memo_warm(arr[:, 0], arr[:, 1],
                                                 bound_m=bound_m)
    except Exception as e:
        logger.warning("malformed profile pairs (skipping pre-warm): %s",
                       e)
        return 0
    metrics.count("datastore.profile.warmed_pairs", warmed)
    return warmed


__all__ = ["export_profile", "load_profile", "warm_matcher",
           "profile_path", "PROFILE_NAME", "WARM_BOUND_M"]
