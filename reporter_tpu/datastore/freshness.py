"""The freshness tier: a bounded recent-delta overlay over the store.

The compacted store (store.py) answers "what is this segment's
hour-of-week profile" from history; a live dashboard asks "what is it
doing *right now*". The gap is the compaction interval: a probe the
worker tee ingested seconds ago sits in a committed delta segment, but
nothing distinguishes it from last month's data, so "the last five
minutes" used to mean a full historical query.

This module closes that gap with a **recent-delta overlay**: a bounded
in-memory ring of the per-partition :class:`~.aggregate.Delta` objects
the ingest path committed, stamped with their arrival time and their
``ingest_key`` (the same exactly-once identity the partition manifests
ledger — so a crash-replayed tee flush dedupes here exactly like it
dedupes on disk, and the overlay can never double-count what the store
refused). Query-time merge happens through :class:`OverlayView`, a
read-only object satisfying the three-method store protocol the query
layer (query.py) is written against — ``partitions()`` /
``live_segments()`` / ``resident_segments()`` — so ``window=`` queries
reuse the sweep/assembler stack unchanged and window-less queries do
not touch this module at all (byte-identical to the pre-overlay
behaviour by construction).

Window semantics (served via ``/histogram?window=…`` and the CLI's
``--window``):

- finite (``5m``, ``300s``, ``2h``): ONLY overlay entries that arrived
  inside the window — the "what changed just now" view;
- ``inf`` (``∞``): the compacted store PLUS overlay entries whose
  append never committed (the tile was spooled for dead-letter replay)
  — so after every append committed and a compaction ran, ``window=∞``
  is byte-identical to the plain query (tests pin this). An
  uncommitted entry re-checks the partition's ``ingested`` ledger at
  query time and drops out permanently once the replay lands.

Memory is bounded and observable: ``REPORTER_TPU_FRESHNESS_MB`` caps
the overlay's byte footprint; hitting it evicts oldest-first with an
``overlay.evicted`` count — never an OOM, never an unbounded queue.

**Materialised viewport summaries** (:class:`ViewportSummaries`) ride
the same tier: tile-level aggregates over each partition's live
segments, refreshed by the background compactor's paced pass (keyed by
manifest seq, so an unchanged partition costs one JSON read), served
as ``/histogram?viewport=1&bbox=…`` — a whole-city dashboard paints
from one read per tile instead of hundreds of segment sweeps.
"""
from __future__ import annotations

import logging
import math
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import metrics
from ..utils import locks as _locks
from .aggregate import Delta, merge_deltas
from .schema import CELLS_PER_SEGMENT, N_SPEED_BINS, SPEED_BIN_KPH

logger = logging.getLogger("reporter_tpu.datastore")

#: per-entry bookkeeping overhead charged against the byte budget on
#: top of the arrays themselves (dict slot, key strings, slots object)
_ENTRY_OVERHEAD_BYTES = 256


def freshness_enabled() -> bool:
    """``REPORTER_TPU_FRESHNESS`` gates the whole tier (default on):
    ``0``/``off``/``false`` makes :meth:`LocalDatastore.enable_freshness`
    a no-op, so every window/feed/viewport surface answers with its
    explicit "tier disabled" error instead of silently serving empty."""
    import os
    return os.environ.get("REPORTER_TPU_FRESHNESS", "1").lower() \
        not in ("", "0", "off", "false")


def overlay_budget_bytes() -> int:
    from ..utils.runtime import _env_int
    return _env_int("REPORTER_TPU_FRESHNESS_MB", 64) * (1 << 20)


def parse_window(spec) -> float:
    """Parse a ``window`` argument into seconds: ``300`` / ``'300'`` /
    ``'90s'`` / ``'5m'`` / ``'2h'`` / ``'1d'``, or ``'inf'`` /
    ``'infinity'`` / ``'∞'`` for the overlay+compacted merge. Shared by
    the /histogram surface and the CLI so the spellings cannot drift."""
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        w = float(spec)
    else:
        text = str(spec).strip().lower()
        if text in ("inf", "infinity", "∞"):
            return math.inf
        mult = 1.0
        if text and text[-1] in "smhd":
            mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[text[-1]]
            text = text[:-1]
        try:
            w = float(text) * mult
        except ValueError:
            raise ValueError(f"bad window {spec!r}: use seconds, "
                             "'<n>s|m|h|d', or 'inf'")
    if w <= 0 or math.isnan(w):
        raise ValueError(f"window must be positive, got {spec!r}")
    return w


class OverlayEntry:
    """One recorded ingest: a partition's delta + its exactly-once key.

    ``in_store`` tracks whether the matching append committed (or was
    deduped by the manifest ledger — either way the compacted store
    carries the rows). ``False`` means the append raised and the tile
    was spooled: those rows exist ONLY here until the dead-letter
    drainer replays them, which is exactly the set ``window=∞`` must
    add on top of the compacted store."""

    __slots__ = ("seq", "ingest_key", "level", "index", "delta",
                 "arrival", "in_store", "map_version", "nbytes")

    def __init__(self, seq: int, ingest_key: Optional[str], level: int,
                 index: int, delta: Delta, arrival: float,
                 in_store: bool, map_version: Optional[str] = None):
        self.seq = seq
        self.ingest_key = ingest_key
        self.level = level
        self.index = index
        self.delta = delta
        self.arrival = arrival
        self.in_store = in_store
        # graph epoch of the producing store (graph/version.py): rides
        # into every /feed event, and a version-pinned window query
        # filters on it — a dashboard spanning a hot swap must not
        # merge deltas from two maps
        self.map_version = map_version
        self.nbytes = _ENTRY_OVERHEAD_BYTES + sum(
            np.asarray(getattr(delta, col)).nbytes
            for col in ("hist_key", "hist_count", "hist_speed_sum",
                        "trans_from", "trans_to", "trans_count"))


class RecentDeltaOverlay:
    """Bounded in-memory ring of recent per-partition deltas.

    Insertion order IS arrival order (one writer path per process), so
    the ring and the dedupe map are one insertion-ordered dict keyed by
    ``(ingest_key, level, index)`` — one flush key spans every
    partition its batch touched, so the partition must be part of the
    identity. Re-offering a recorded key is a counted no-op (the same
    contract the manifest ledger gives the store), which is what makes
    a crash-restarted tee replay safe: the store dedupes on disk, the
    overlay dedupes here, and neither ever double-counts."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 clock=time.time):
        self.budget_bytes = budget_bytes if budget_bytes is not None \
            else overlay_budget_bytes()
        self.clock = clock
        self._lock = _locks.new_lock("freshness.overlay")
        self._entries: "OrderedDict[tuple, OverlayEntry]" = OrderedDict()
        self._bytes = 0
        self._seq = 0
        self._evicted = 0

    @property
    def cursor(self) -> int:
        """Monotone per-store record counter (the feed's cursor base)."""
        return self._seq

    def record(self, level: int, index: int, delta: Delta,
               ingest_key: Optional[str],
               in_store: bool = True,
               map_version: Optional[str] = None
               ) -> Optional[OverlayEntry]:
        """Record one ingested partition delta; None when the key was
        already recorded (the dedupe no-op — a True ``in_store`` still
        upgrades the existing entry, so a spooled-then-replayed flush
        stops counting as overlay-only once its replay commits)."""
        arrival = self.clock()
        with self._lock:
            if ingest_key is not None:
                key = (ingest_key, int(level), int(index))
                got = self._entries.get(key)
                if got is not None:
                    metrics.count("overlay.deduped")
                    if in_store and not got.in_store:
                        got.in_store = True
                    return None
            else:
                # keyless ingest (ad-hoc CSV): no cross-restart identity
                # to dedupe on — record under a per-process unique key
                key = ("_anon", self._seq + 1, int(level), int(index))
            self._seq += 1
            entry = OverlayEntry(self._seq, ingest_key, int(level),
                                 int(index), delta, arrival, in_store,
                                 map_version=map_version)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            metrics.count("overlay.records")
            while self._bytes > self.budget_bytes and len(self._entries) > 1:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self._evicted += 1
                metrics.count("overlay.evicted")
            return entry

    def window_deltas(self, window_s: float,
                      now: Optional[float] = None,
                      map_version: Optional[str] = None
                      ) -> Dict[Tuple[int, int], List[Delta]]:
        """Per-partition deltas that arrived within ``window_s`` of now
        — the finite-window view's entire contents. A ``map_version``
        pin drops entries stamped with a DIFFERENT epoch (untagged
        legacy entries pass, matching EpochView's on-disk rule)."""
        horizon = (now if now is not None else self.clock()) - window_s
        out: Dict[Tuple[int, int], List[Delta]] = {}
        with self._lock:
            for e in self._entries.values():
                if map_version is not None \
                        and e.map_version is not None \
                        and e.map_version != map_version:
                    continue
                if e.arrival >= horizon:
                    out.setdefault((e.level, e.index), []).append(e.delta)
        return out

    def uncommitted_deltas(self, store,
                           map_version: Optional[str] = None
                           ) -> Dict[Tuple[int, int], List[Delta]]:
        """Per-partition deltas the compacted store does NOT carry —
        the only thing ``window=∞`` adds on top of it. Each candidate
        re-checks its partition's ``ingested`` ledger (one manifest
        read per touched partition, memoised across the call) and
        flips to committed permanently once the replay landed, so the
        merged view converges back to byte-identity with the plain
        query on its own."""
        with self._lock:
            pending = [e for e in self._entries.values()
                       if not e.in_store]
        out: Dict[Tuple[int, int], List[Delta]] = {}
        ledgers: Dict[str, dict] = {}
        for e in pending:
            if map_version is not None and e.map_version is not None \
                    and e.map_version != map_version:
                continue
            pdir = store.partition_dir(e.level, e.index)
            if pdir not in ledgers:
                ledgers[pdir] = store._read_manifest(pdir).get(
                    "ingested", {})
            if e.ingest_key is not None and e.ingest_key in ledgers[pdir]:
                # benign race with a concurrent flip: idempotent write
                e.in_store = True
                metrics.count("overlay.committed")
                continue
            out.setdefault((e.level, e.index), []).append(e.delta)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget_bytes": self.budget_bytes,
                    "cursor": self._seq, "evicted": self._evicted}


class OverlayView:
    """Read-only store facade over in-memory deltas, optionally stacked
    on the compacted store — satisfies exactly the three-method
    protocol the query layer uses (``partitions`` / ``live_segments``
    / ``resident_segments``), so ``query_segment`` / ``query_many`` /
    ``query_bbox`` serve windowed answers through the same swept code
    path as historical ones."""

    def __init__(self, extra: Dict[Tuple[int, int], List[Delta]],
                 base=None):
        self._extra = extra
        self._base = base

    def partitions(self) -> Iterator[Tuple[int, int]]:
        seen = set()
        if self._base is not None:
            for part in self._base.partitions():
                seen.add(part)
                yield part
        for part in sorted(self._extra):
            if part not in seen:
                yield part

    def live_segments(self, level: int, index: int) -> List[Delta]:
        out: List[Delta] = []
        if self._base is not None:
            out.extend(self._base.live_segments(level, index))
        out.extend(self._extra.get((int(level), int(index)), []))
        return out

    def resident_segments(self, level: int, index: int) -> np.ndarray:
        parts = []
        if self._base is not None:
            parts.append(np.asarray(
                self._base.resident_segments(level, index),
                dtype=np.int64))
        for delta in self._extra.get((int(level), int(index)), []):
            parts.append(np.unique(
                np.asarray(delta.hist_key) // CELLS_PER_SEGMENT))
        return np.unique(np.concatenate(parts)) if parts \
            else np.zeros(0, dtype=np.int64)


class ViewportSummaries:
    """Materialised tile-level aggregates over the compacted store.

    ``refresh()`` (the background compactor's paced pass — never the
    request path) merges each partition's live segments into one
    summary dict, memoised by the partition manifest's ``seq`` so an
    unchanged partition costs one small JSON read. ``summarise()``
    intersects a bbox with the materialised tiles — a whole-city
    viewport is one dict lookup per covered tile, not hundreds of
    per-segment sweeps."""

    def __init__(self, store):
        self._store = store
        self._lock = _locks.new_lock("freshness.viewports")
        self._tiles: Dict[Tuple[int, int], dict] = {}
        self._seqs: Dict[Tuple[int, int], int] = {}
        self._refreshes = 0

    def refresh(self) -> dict:
        """One materialisation pass; returns {"tiles", "refreshed"}."""
        refreshed = 0
        live = set()
        for level, index in list(self._store.partitions()):
            live.add((level, index))
            pdir = self._store.partition_dir(level, index)
            seq = self._store._read_manifest(pdir)["seq"]
            with self._lock:
                if self._seqs.get((level, index)) == seq:
                    continue
            summary = self._summarise_partition(level, index)
            with self._lock:
                self._tiles[(level, index)] = summary
                self._seqs[(level, index)] = seq
            refreshed += 1
        with self._lock:
            for gone in [k for k in self._tiles if k not in live]:
                del self._tiles[gone]
                del self._seqs[gone]
            self._refreshes += 1
            n = len(self._tiles)
        if refreshed:
            metrics.count("viewport.refreshed_tiles", refreshed)
        return {"tiles": n, "refreshed": refreshed}

    def _summarise_partition(self, level: int, index: int) -> dict:
        parts = self._store.live_segments(level, index)
        if not parts:
            return {"level": int(level), "tile_index": int(index),
                    "n_segments": 0, "count": 0, "mean_kph": None,
                    "hours_covered": 0,
                    "histogram": {"bin_kph": SPEED_BIN_KPH,
                                  "counts": [0] * N_SPEED_BINS}}
        merged = merge_deltas(parts)
        keys = np.asarray(merged.hist_key)
        counts = np.asarray(merged.hist_count)
        sums = np.asarray(merged.hist_speed_sum)
        cell = keys % CELLS_PER_SEGMENT
        bins = np.zeros(N_SPEED_BINS, dtype=np.int64)
        np.add.at(bins, cell % N_SPEED_BINS, counts)
        total = int(counts.sum())
        return {
            "level": int(level), "tile_index": int(index),
            "n_segments": int(np.unique(keys
                                        // CELLS_PER_SEGMENT).shape[0]),
            "count": total,
            "mean_kph": round(float(sums.sum()) / total, 3)
            if total else None,
            "hours_covered": int(np.unique(cell
                                           // N_SPEED_BINS).shape[0]),
            "histogram": {"bin_kph": SPEED_BIN_KPH,
                          "counts": bins.tolist()},
        }

    def summarise(self, bbox: Sequence[float], level: int) -> dict:
        """Viewport answer from the materialised tiles (refreshing
        lazily exactly once if no compactor pass ran yet). The bbox
        intersection reuses the query layer's antimeridian-aware
        row/col range math."""
        from .query import _bbox_ranges
        with self._lock:
            fresh_needed = self._refreshes == 0
        if fresh_needed:
            self.refresh()
        metrics.count("viewport.queries")
        ranges = _bbox_ranges(bbox, int(level))
        with self._lock:
            tiles = [dict(summary) for (lvl, index), summary
                     in sorted(self._tiles.items())
                     if lvl == int(level)
                     and any(r0 <= index // ncols <= r1
                             and c0 <= index % ncols <= c1
                             for r0, r1, c0, c1, ncols in ranges)]
        return {"bbox": [float(v) for v in bbox], "level": int(level),
                "n_tiles": len(tiles),
                "count": sum(t["count"] for t in tiles),
                "tiles": tiles}

    def snapshot(self) -> dict:
        with self._lock:
            return {"tiles": len(self._tiles),
                    "refreshes": self._refreshes}


class FreshnessTier:
    """The per-process bundle: overlay + change feed + viewport
    summaries, attached to a store as ``store.freshness`` (the ingest
    path's hook point — store.py records every appended delta here,
    whatever producer drove it: the worker tee, a dead-letter replay,
    the CLI)."""

    def __init__(self, store, clock=None,
                 budget_bytes: Optional[int] = None):
        from .feed import ChangeFeed
        self.store = store
        self.clock = clock or time.time
        self.overlay = RecentDeltaOverlay(budget_bytes=budget_bytes,
                                          clock=self.clock)
        self.feed = ChangeFeed(store, clock=self.clock)
        self.viewports = ViewportSummaries(store)

    def record(self, level: int, index: int, delta: Delta,
               ingest_key: Optional[str], in_store: bool = True,
               map_version: Optional[str] = None) -> None:
        """Ingest-path hook (store.py): record + publish. Never raises
        — a freshness failure must not fail the durable ingest."""
        try:
            entry = self.overlay.record(level, index, delta, ingest_key,
                                        in_store=in_store,
                                        map_version=map_version)
            if entry is not None:
                self.feed.publish_delta(entry)
        except Exception as e:
            metrics.count("overlay.record_errors")
            logger.error("freshness record failed for %d/%d: %s",
                         level, index, e)

    def query_view(self, window_s: float,
                   map_version: Optional[str] = None):
        """The store-protocol view a ``window=`` query sweeps: finite →
        overlay-only entries inside the window; ``inf`` → compacted
        store + overlay entries the store does not carry. A
        ``map_version`` pin filters both layers to one graph epoch."""
        metrics.count("overlay.window_queries")
        if math.isinf(window_s):
            base = self.store
            if map_version is not None:
                from .store import EpochView
                base = EpochView(self.store, map_version)
            return OverlayView(
                self.overlay.uncommitted_deltas(
                    self.store, map_version=map_version),
                base=base)
        return OverlayView(self.overlay.window_deltas(
            window_s, map_version=map_version))

    def on_compactor_pass(self) -> None:
        """The background compactor's paced hook: refresh viewport
        materialisations and run one store-watch sweep so feed
        subscribers in THIS process see commits other processes made
        (the pre-fork fleet's overlays are per-process)."""
        self.viewports.refresh()
        self.feed.watch_store()

    def snapshot(self) -> dict:
        return {"overlay": self.overlay.snapshot(),
                "feed": self.feed.snapshot(),
                "viewports": self.viewports.snapshot()}


__all__ = ["FreshnessTier", "RecentDeltaOverlay", "OverlayView",
           "OverlayEntry", "ViewportSummaries", "parse_window",
           "freshness_enabled", "overlay_budget_bytes"]
