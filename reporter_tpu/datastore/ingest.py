"""Tile ingestion: flushed CSV tiles -> columnar observation batches.

Two entry paths:

- :func:`parse_tile_csv` reads one flushed tile payload (the anonymiser's
  CSV, ``Segment.column_layout`` header) into an
  :class:`~reporter_tpu.datastore.schema.ObservationBatch` — one pass
  over the lines to split, then whole-column numpy conversion.
- :func:`scan_tiles` walks an anonymiser output directory (the
  ``{t0}_{t1}/{level}/{tile_index}/{source}.{uuid}`` layout, which the
  dead-letter spool mirrors) and yields tile file paths, so
  ``datastore_cli ingest`` replays a results dir and a
  ``.deadletter`` dir with the same code.

This module is in the declared lint hot set: past the sanctioned
``parse_tile_csv`` line split, everything stays columnar.
"""
from __future__ import annotations

import logging
import os
from typing import Iterator, Optional

import numpy as np

from ..utils import metrics
from .lease import LeaseHeldElsewhere
from .schema import INVALID_SEGMENT_ID, ObservationBatch

logger = logging.getLogger("reporter_tpu.datastore")

_HEADER_PREFIX = "segment_id,"
_N_COLUMNS = 10


def parse_tile_csv(payload: str) -> ObservationBatch:
    """Parse one tile CSV payload (header optional) into columns.

    Rows with the wrong column count are dropped (counted in
    ``datastore.ingest.bad_rows``) rather than failing the tile: a
    dead-letter replay must not wedge on one truncated flush.
    """
    with metrics.timer("datastore.ingest.parse"):
        lines = payload.strip("\n").split("\n")
        if lines and lines[0].startswith(_HEADER_PREFIX):
            lines = lines[1:]
        cells = [ln.split(",") for ln in lines if ln]
        bad = sum(1 for row in cells if len(row) != _N_COLUMNS)
        if bad:
            metrics.count("datastore.ingest.bad_rows", bad)
            cells = [row for row in cells if len(row) == _N_COLUMNS]
        if not cells:
            return ObservationBatch.empty()
        cols = list(zip(*cells))
        nxt = np.array(cols[1], dtype=object)
        nxt[nxt == ""] = INVALID_SEGMENT_ID
        return ObservationBatch(
            segment_id=np.array(cols[0], dtype=np.int64),
            next_id=nxt.astype(np.int64),
            duration_s=np.array(cols[2], dtype=np.float64),
            count=np.array(cols[3], dtype=np.int64),
            length_m=np.array(cols[4], dtype=np.int64),
            queue_m=np.array(cols[5], dtype=np.int64),
            min_ts=np.array(cols[6], dtype=np.int64),
            max_ts=np.array(cols[7], dtype=np.int64),
        )


def scan_tiles(root: str,
               skip_names: tuple = (".deadletter", ".traces",
                                    ".flightrec",
                                    ".quarantine")) -> Iterator[str]:
    """Yield tile file paths under an anonymiser output (or dead-letter)
    directory, skipping the dead-letter spool, the batcher's trace-JSON
    spool (``.traces`` — request bodies, not tile CSV), the flight
    recorder's postmortem dumps (``.flightrec`` — span JSON), the
    replayer's poison quarantine (``.quarantine`` — entries that beat
    the replay budget, manual autopsy only) and dot-state files when
    scanning a results root. The dot-file skip also covers the store's
    own control artifacts when a store root is (mis)scanned: the
    ``.lease`` writer-lease file and the ``.profile`` route-memo
    pre-warm artifact are coordination state, never tile CSV."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in skip_names)
        for name in sorted(filenames):
            if name.startswith("."):
                continue
            yield os.path.join(dirpath, name)


def ingest_file(store, path: str,
                ingest_key: Optional[str] = None) -> int:
    """Parse + aggregate + append one tile file; returns rows ingested.
    ``ingest_key`` (the file's flush identity — its relpath under the
    scan root) makes the append idempotent via the partition ledger."""
    with open(path, "r", encoding="utf-8") as f:
        obs = parse_tile_csv(f.read())
    return store.ingest(obs, ingest_key=ingest_key)


def ingest_dir(store, root: str, delete: bool = False,
               limit: Optional[int] = None) -> dict:
    """Replay every tile file under ``root`` into ``store``.

    Exactly-once: each file's relpath under ``root`` — which IS the
    flush identity ``{t0}_{t1}/{level}/{tile}/{source}[.writer].e{epoch}``
    the anonymiser stamps, in the output dir and the dead-letter spool
    alike — rides the append as its ledger key, so replaying a
    directory the store (or the worker's tee) already ingested is a
    counted no-op and an ``ingest --delete`` interrupted between append
    and unlink cannot double-count on the re-run.

    ``delete=True`` removes each file after a successful append — the
    dead-letter replay contract (a replayed tile must not double-count
    on the next replay). A file that FAILS mid-ingest is quarantined
    (renamed to ``.<name>.failed``, which :func:`scan_tiles` skips) for
    the same reason: a multi-partition tile may have durably committed
    some partitions' deltas before the error, so blindly replaying it
    would double-count those (the ledger shields exactly the partitions
    that committed). Quarantined files keep the unappended rows for
    manual recovery. Returns ``{"files", "rows", "failures"}`` (plus
    ``"aborted": true`` when a mid-replay writer-lease loss stopped
    the pass — files intact and replayable, NOT counted as failures).
    """
    files = rows = failures = 0
    aborted = False
    # writer-lease gate up front: a replay against a store another
    # process owns must refuse loudly BEFORE touching any tile — not
    # quarantine every file as "failed"
    store.lease.require()
    with metrics.timer("datastore.ingest.dir"):
        for path in scan_tiles(root):
            if limit is not None and files >= limit:
                break
            key = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                rows += ingest_file(store, path, ingest_key=key)
            except LeaseHeldElsewhere:
                # stolen mid-replay (our lease expired under load): the
                # file is intact and replayable — abort the pass, do
                # NOT quarantine, and do NOT count it as a failure
                # ("failures" means quarantined files; this is a
                # healthy retryable abort, flagged separately)
                logger.warning("writer lease lost mid-replay of %s; "
                               "aborting (files left for the next run)",
                               root)
                aborted = True
                break
            except Exception as e:
                logger.error("could not ingest %s (quarantining): %s",
                             path, e)
                failures += 1
                metrics.count("datastore.ingest.quarantined")
                try:
                    d, name = os.path.split(path)
                    os.replace(path, os.path.join(d, f".{name}.failed"))
                except OSError as re:
                    logger.error("could not quarantine %s: %s", path, re)
                continue
            files += 1
            if delete:
                os.unlink(path)
    metrics.count("datastore.ingest.files", files)
    out = {"files": files, "rows": rows, "failures": failures}
    if aborted:
        # a mid-replay lease loss: nothing quarantined, everything
        # left replayable — distinct from "failures" (quarantined)
        out["aborted"] = True
    return out


__all__ = ["parse_tile_csv", "scan_tiles", "ingest_file", "ingest_dir"]
