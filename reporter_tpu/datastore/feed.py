"""The change feed: a monotone per-store cursor over recent ingests.

Dashboards used to poll ``/histogram?bbox=…`` on a timer — every viewer
pays a full bbox sweep whether anything changed or not. The feed
inverts that: ``/feed?bbox=…&cursor=N`` long-polls until an ingest
lands inside the viewport (or the timeout elapses), and each response
carries the next cursor, so a subscriber sees every change exactly once
in order and sweeps only when told something moved.

Two event sources, one cursor:

- **delta events** — the in-process ingest hook (freshness.py): the
  worker tee's flush publishes the touched partition + its segment ids
  the instant the append commits. In-process delivery is a condition
  notify — no sleep-polling anywhere on this path.
- **tile events** — the store watcher: the pre-fork fleet's overlays
  are per-process, so a serving process long-polling on behalf of a
  subscriber periodically diffs every partition's manifest ``seq``
  (``REPORTER_TPU_FRESHNESS_POLL_S`` paces it; one designated waiter
  scans per tick, never the whole herd) and publishes a segment-less
  "this tile changed" event for commits another process made.

Cursor semantics (pinned in tests, documented in README): the cursor
is a monotone per-process integer; ``cursor=N`` returns events with
``seq > N``; ``cursor=-1`` means "from now". The event ring is bounded
(``RING_EVENTS``); a subscriber whose cursor fell behind the ring gets
``resync: true`` and must re-query its viewport once — loss is always
EXPLICIT, never silent.

Load shedding (PR 14 semantics): the waiter table is bounded
(``REPORTER_TPU_FRESHNESS_WAITERS``); past it, a poll sheds
immediately with :class:`FeedOverload` → 429 + ``Retry-After`` — an
explicit retry signal, before the long-poll would pin another handler
thread. The serving layer additionally sheds subscribers when the
pressure ladder climbs (server.py), so feed fan-out degrades before
the match path does.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import metrics
from ..utils import locks as _locks
from .schema import CELLS_PER_SEGMENT

#: bounded event ring: a subscriber further behind than this must
#: resync (re-query its viewport) — the bound is what keeps an idle
#: subscriber from pinning unbounded history in memory
RING_EVENTS = 4096

#: max segment ids carried per delta event (a huge flush truncates,
#: explicitly — the subscriber re-queries the tile instead)
EVENT_SEGMENTS_CAP = 256

#: back-off handed to a shed subscriber (seconds) — the same
#: Retry-After contract the admission gate's 429s carry
FEED_RETRY_AFTER_S = 5


def max_waiters() -> int:
    from ..utils.runtime import _env_int
    return _env_int("REPORTER_TPU_FRESHNESS_WAITERS", 1024)


def watch_pace_s() -> float:
    from ..utils.runtime import _env_float
    return _env_float("REPORTER_TPU_FRESHNESS_POLL_S", 0.25)


class FeedOverload(RuntimeError):
    """A shed subscriber's explicit retry signal (mirrors
    service.admission.Overload's shape so the HTTP layer maps both to
    429 + Retry-After through one path)."""

    def __init__(self, reason: str, retry_after_s: int):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


class FeedEvent:
    """One change: a partition that gained data. ``kind`` is ``delta``
    (in-process ingest, carries segment ids) or ``tile`` (store
    watcher, another process committed — segment ids unknown here, the
    subscriber sweeps the tile)."""

    __slots__ = ("seq", "kind", "level", "index", "segments",
                 "truncated", "rows", "arrival", "map_version")

    def __init__(self, seq: int, kind: str, level: int, index: int,
                 segments: List[int], truncated: bool, rows: int,
                 arrival: float, map_version: Optional[str] = None):
        self.seq = seq
        self.kind = kind
        self.level = level
        self.index = index
        self.segments = segments
        self.truncated = truncated
        self.rows = rows
        self.arrival = arrival
        # graph epoch of the producing ingest (graph/version.py); an
        # ``epoch`` event announces a hot swap and carries the NEW
        # version — a cursor held across the swap sees the boundary
        # explicitly instead of merging deltas from two maps
        self.map_version = map_version

    def to_wire(self) -> dict:
        out = {"seq": self.seq, "kind": self.kind, "level": self.level,
               "tile_index": self.index, "segments": self.segments,
               "rows": self.rows, "arrival": round(self.arrival, 3)}
        if self.truncated:
            out["truncated"] = True
        if self.map_version is not None:
            out["map_version"] = self.map_version
        return out


class ChangeFeed:
    """Monotone cursor + bounded event ring + long-poll waiters over
    one store (see module docstring)."""

    def __init__(self, store, clock=None,
                 max_waiters_n: Optional[int] = None,
                 ring_events: int = RING_EVENTS):
        self.store = store
        self.clock = clock or time.time
        self.max_waiters = max_waiters_n if max_waiters_n is not None \
            else max_waiters()
        self._cond = threading.Condition(_locks.new_lock("freshness.feed"))
        self._ring: "deque[FeedEvent]" = deque(maxlen=ring_events)
        self._seq = 0
        self._waiters = 0
        self._shed = 0
        # store watcher state: per-partition manifest seq at last scan;
        # None until the first scan baselines (which emits nothing — a
        # fresh feed must not replay the store's whole history)
        self._watch_seqs: Optional[Dict[Tuple[int, int], int]] = None
        self._watch_lock = _locks.new_lock("freshness.feed.watch")
        self._watch_last = 0.0

    # -- publishing --------------------------------------------------------
    def publish_delta(self, entry) -> None:
        """In-process ingest hook: one committed OverlayEntry becomes
        one delta event; every waiter is notified (condition — the
        e2e freshness proof's "no sleep-polling" path)."""
        segs = np.unique(
            np.asarray(entry.delta.hist_key) // CELLS_PER_SEGMENT)
        truncated = segs.shape[0] > EVENT_SEGMENTS_CAP
        self._publish("delta", entry.level, entry.index,
                      segs[:EVENT_SEGMENTS_CAP].tolist(), truncated,
                      int(entry.delta.rows),
                      map_version=getattr(entry, "map_version", None))

    def publish_epoch(self, map_version: str) -> None:
        """Announce a graph epoch boundary (a city hot swap flipped):
        one ``epoch`` event carrying the NEW map_version. Delivered to
        EVERY subscriber regardless of bbox/level filter — the
        resync-style contract: whatever viewport a dashboard watches,
        its history predates the new map, so it must re-query once and
        drop cross-epoch merges."""
        metrics.count("datastore.epoch.events")
        self._publish("epoch", -1, -1, [], False, 0,
                      map_version=str(map_version))

    def _publish(self, kind: str, level: int, index: int,
                 segments: List[int], truncated: bool, rows: int,
                 map_version: Optional[str] = None) -> None:
        with self._cond:
            self._seq += 1
            self._ring.append(FeedEvent(self._seq, kind, int(level),
                                        int(index), segments, truncated,
                                        rows, self.clock(),
                                        map_version=map_version))
            metrics.count("feed.events")
            self._cond.notify_all()

    # -- store watcher -----------------------------------------------------
    def watch_store(self, force: bool = False) -> int:
        """One manifest-seq diff over the store's partitions; publishes
        a tile event per partition whose seq moved since the last scan
        (commits made by OTHER processes — in-process commits already
        published richer delta events, so the watcher only reports a
        seq this process's publishes have not already covered… it
        cannot tell, so cross-process subscribers may see a tile event
        duplicating a delta event; the cursor makes that harmless and
        the README documents it as at-least-once per change).

        Paced: callers invoke this freely (every long-poll wait slice,
        every compactor pass); a non-forced call inside the pace window
        or while another thread scans is a no-op (pacing is wall-clock
        — time.monotonic — for the same frozen-fake reason as the poll
        deadline). Returns events published."""
        now = time.monotonic()
        if not self._watch_lock.acquire(blocking=False):
            return 0
        try:
            if not force and now - self._watch_last < watch_pace_s():
                return 0
            self._watch_last = now
            metrics.count("feed.watch.passes")
            seqs: Dict[Tuple[int, int], int] = {}
            for level, index in list(self.store.partitions()):
                pdir = self.store.partition_dir(level, index)
                seqs[(level, index)] = \
                    self.store._read_manifest(pdir)["seq"]
            if self._watch_seqs is None:
                self._watch_seqs = seqs  # baseline: emit nothing
                return 0
            published = 0
            for part, seq in seqs.items():
                if seq > self._watch_seqs.get(part, 0):
                    self._publish("tile", part[0], part[1], [], False, 0)
                    published += 1
            self._watch_seqs = seqs
            return published
        finally:
            self._watch_lock.release()

    # -- subscribing -------------------------------------------------------
    def _collect(self, cursor: int, level: Optional[int],
                 ranges: Optional[list], max_events: int
                 ) -> Tuple[List[FeedEvent], bool]:
        """(matching events with seq > cursor, resync) — caller holds
        the condition. ``resync`` is True when events older than the
        ring's tail were dropped past this cursor: the subscriber's
        next step is one full viewport query, not trust in the gap."""
        base = self._seq - len(self._ring)
        resync = cursor < base
        out: List[FeedEvent] = []
        for ev in self._ring:
            if ev.seq <= cursor:
                continue
            if ev.kind == "epoch":
                # epoch boundaries bypass viewport filters: every
                # subscriber's held history predates the new map
                out.append(ev)
                if len(out) >= max_events:
                    break
                continue
            if level is not None and ev.level != level:
                continue
            if ranges is not None and not any(
                    r0 <= ev.index // ncols <= r1
                    and c0 <= ev.index % ncols <= c1
                    for r0, r1, c0, c1, ncols in ranges):
                continue
            out.append(ev)
            if len(out) >= max_events:
                break
        return out, resync

    def poll(self, bbox: Optional[Sequence[float]] = None,
             level: Optional[int] = None, cursor: int = -1,
             timeout_s: float = 25.0, max_events: int = 256) -> dict:
        """One long-poll: block until an event lands past ``cursor``
        inside the bbox (condition-notified in process; the store
        watcher picks up cross-process commits between wait slices) or
        the timeout elapses. Raises :class:`FeedOverload` past the
        waiter cap — shed BEFORE waiting, so a shed costs headers, not
        a pinned slot.

        The timeout is wall-clock by design — ``time.monotonic``, NOT
        the injected ``clock`` (which stamps arrivals and can be a
        test-frozen fake: a frozen deadline would spin this loop
        forever)."""
        ranges = None
        if bbox is not None:
            from .query import _bbox_ranges
            if level is None:
                raise ValueError("bbox subscriptions need a level")
            ranges = _bbox_ranges(bbox, int(level))
        cursor = int(cursor)
        metrics.count("feed.polls")
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        with self._cond:
            if self._waiters >= self.max_waiters:
                self._shed += 1
                metrics.count("feed.shed.waiters")
                raise FeedOverload("feed_waiters", FEED_RETRY_AFTER_S)
            if cursor < 0:
                cursor = self._seq  # "from now"
            self._waiters += 1
        try:
            while True:
                with self._cond:
                    events, resync = self._collect(cursor, level, ranges,
                                                   int(max_events))
                    now = time.monotonic()
                    if events or resync or now >= deadline:
                        new_cursor = events[-1].seq if events \
                            else max(cursor, self._seq - len(self._ring))
                        if events:
                            metrics.count("feed.delivered", len(events))
                        else:
                            metrics.count("feed.timeouts")
                        if resync:
                            metrics.count("feed.resync")
                        return {"cursor": new_cursor,
                                "events": [e.to_wire() for e in events],
                                "resync": resync,
                                "timeout": not events and not resync}
                    self._cond.wait(min(watch_pace_s(),
                                        max(0.0, deadline - now)))
                # outside the condition: the paced cross-process scan
                # (manifest reads must never run under the waiter lock)
                self.watch_store()
        finally:
            with self._cond:
                self._waiters -= 1

    @property
    def cursor(self) -> int:
        return self._seq

    def snapshot(self) -> dict:
        with self._cond:
            return {"cursor": self._seq, "events": len(self._ring),
                    "waiters": self._waiters,
                    "max_waiters": self.max_waiters,
                    "shed": self._shed}


__all__ = ["ChangeFeed", "FeedEvent", "FeedOverload", "RING_EVENTS",
           "FEED_RETRY_AFTER_S", "max_waiters", "watch_pace_s"]
