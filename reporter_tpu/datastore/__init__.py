"""The datastore: the consumer of flushed traffic tiles.

The reporter half (matcher + streaming worker) emits anonymised,
time-quantised segment tiles; this subsystem closes the loop the way the
reference ecosystem's companion datastore service did — turning tiles
into per-segment speed histograms and answering queries:

- :mod:`schema`     — histogram axes, composite keys, columnar batch
- :mod:`ingest`     — tile CSV / in-process ``Segment`` ingestion
- :mod:`aggregate`  — whole-batch searchsorted/add.at histogram kernel
- :mod:`store`      — append-only columnar partitions, atomic commits,
  mmap reads, compaction
- :mod:`query`      — mean / percentiles / coverage / transitions,
  batched multi-segment + bbox sweeps
- :mod:`lease`      — the cross-process writer lease every mutating
  entry point holds
- :mod:`compactor`  — background delta-pressure compaction (lease-owned)
- :mod:`freshness`  — recent-delta overlay (``window=`` queries) +
  materialised viewport summaries
- :mod:`feed`       — bbox change feed: monotone cursor, long-poll,
  bounded waiters
- :mod:`profile`    — per-city route-memo pre-warm artifact

:class:`LocalDatastore` is the one-stop facade the service's
``/histogram`` action, ``datastore_cli``, and the streaming worker's tee
all share.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .aggregate import Delta, aggregate, merge_deltas
from .compactor import BackgroundCompactor
from .feed import ChangeFeed, FeedOverload
from .freshness import (
    FreshnessTier,
    OverlayView,
    RecentDeltaOverlay,
    freshness_enabled,
    parse_window,
)
from .ingest import ingest_dir, ingest_file, parse_tile_csv, scan_tiles
from .lease import LeaseHeldElsewhere, StoreLease
from .profile import export_profile, load_profile, profile_path, warm_matcher
from .query import (
    DEFAULT_PERCENTILES,
    hours_for_range,
    parse_hours_spec,
    query_bbox,
    query_many,
    query_segment,
)
from .schema import ObservationBatch
from .store import EpochView, HistogramStore


class LocalDatastore(HistogramStore):
    """A histogram store plus its query surface, rooted at a directory."""

    def ingest_segments(self, segments,
                        max_deltas: Optional[int] = None,
                        max_delta_bytes: Optional[int] = None,
                        ingest_key: Optional[str] = None) -> int:
        """Zero-serialisation path: aggregate culled ``Segment`` structs
        straight out of the anonymiser's flush, no CSV round trip. With
        compaction thresholds, the touched partitions are pressure-
        checked inline (the worker tee's automatic-compaction knobs).
        ``ingest_key`` — the flush identity the anonymiser passes its
        tee — makes the ingest idempotent (partition manifest ledger):
        a crash-replayed flush re-offers the same key and no-ops."""
        return self.ingest(ObservationBatch.from_segments(segments),
                           max_deltas=max_deltas,
                           max_delta_bytes=max_delta_bytes,
                           ingest_key=ingest_key)

    def ingest_csv(self, payload: str) -> int:
        return self.ingest(parse_tile_csv(payload))

    def ingest_dir(self, root: str, delete: bool = False,
                   limit: Optional[int] = None) -> dict:
        return ingest_dir(self, root, delete=delete, limit=limit)

    def enable_freshness(self, clock=None, budget_bytes=None):
        """Attach the freshness tier (freshness.py) — the recent-delta
        overlay, change feed and viewport summaries — honouring the
        ``REPORTER_TPU_FRESHNESS`` gate. Idempotent; returns the tier
        (or None when the gate disables it)."""
        if self.freshness is None and freshness_enabled():
            self.freshness = FreshnessTier(self, clock=clock,
                                           budget_bytes=budget_bytes)
        return self.freshness

    def _query_store(self, window, map_version: Optional[str] = None,
                     merge: bool = False):
        """The store the query layer should sweep for this request:
        ``window=None`` is ALWAYS ``self`` (the pre-freshness path,
        byte-identical by construction); a window resolves through the
        overlay. A process without the tier serves ``inf`` as the
        plain compacted store (the overlay would add nothing) and a
        finite window as empty (it has witnessed no recent ingests —
        windows need the tee co-located, see README).

        Epoch pin/merge semantics (graph versioning): the effective
        pin is the explicit ``map_version=`` if given, else the store's
        ACTIVE version (the latest map build this process serves) —
        histograms never silently mix epochs. ``merge=True`` is the
        explicit opt-in that sweeps every epoch (and is mutually
        exclusive with an explicit pin); a store with no version
        (every pre-versioning deployment) behaves exactly as before."""
        from ..utils import metrics
        if merge and map_version is not None:
            raise ValueError(
                "merge and map_version are mutually exclusive")
        pin = None
        if not merge:
            pin = map_version if map_version is not None \
                else self.map_version
        if pin is not None:
            metrics.count("datastore.epoch.pinned_queries")
        elif merge:
            metrics.count("datastore.epoch.merged_queries")
        if window is None:
            if pin is not None:
                from .store import EpochView
                return EpochView(self, pin)
            return self
        import math
        w = parse_window(window)
        if self.freshness is not None:
            return self.freshness.query_view(w, map_version=pin)
        if math.isinf(w):
            if pin is not None:
                from .store import EpochView
                return EpochView(self, pin)
            return self
        return OverlayView({})

    def query(self, segment_id: int,
              hours: Optional[Sequence[int]] = None,
              percentiles: Sequence[float] = DEFAULT_PERCENTILES,
              max_transitions: int = 32, window=None,
              map_version: Optional[str] = None,
              merge: bool = False) -> dict:
        return query_segment(
            self._query_store(window, map_version, merge), segment_id,
            hours=hours, percentiles=percentiles,
            max_transitions=max_transitions)

    def query_many(self, segment_ids,
                   hours: Optional[Sequence[int]] = None,
                   percentiles: Sequence[float] = DEFAULT_PERCENTILES,
                   max_transitions: int = 32, window=None,
                   map_version: Optional[str] = None,
                   merge: bool = False) -> list:
        """Batched spelling of :meth:`query`: one sweep per partition's
        live segment files serves the whole id list (datastore/query.py)
        — answer-identical to N single queries by construction."""
        return query_many(
            self._query_store(window, map_version, merge), segment_ids,
            hours=hours, percentiles=percentiles,
            max_transitions=max_transitions)

    def query_bbox(self, bbox, level: int,
                   hours: Optional[Sequence[int]] = None,
                   percentiles: Sequence[float] = DEFAULT_PERCENTILES,
                   max_transitions: int = 32,
                   max_segments: Optional[int] = None,
                   window=None,
                   map_version: Optional[str] = None,
                   merge: bool = False) -> dict:
        kwargs = {}
        if max_segments is not None:
            kwargs["max_segments"] = max_segments
        return query_bbox(
            self._query_store(window, map_version, merge), bbox, level,
            hours=hours, percentiles=percentiles,
            max_transitions=max_transitions, **kwargs)


__all__ = [
    "BackgroundCompactor", "ChangeFeed", "Delta", "EpochView",
    "FeedOverload",
    "FreshnessTier", "HistogramStore", "LeaseHeldElsewhere",
    "LocalDatastore", "ObservationBatch", "OverlayView",
    "RecentDeltaOverlay", "freshness_enabled", "parse_window",
    "StoreLease", "aggregate", "merge_deltas", "parse_tile_csv",
    "scan_tiles", "ingest_file", "ingest_dir", "query_segment",
    "query_many", "query_bbox", "hours_for_range", "parse_hours_spec",
    "export_profile", "load_profile", "warm_matcher", "profile_path",
    "DEFAULT_PERCENTILES",
]
