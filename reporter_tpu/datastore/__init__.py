"""The datastore: the consumer of flushed traffic tiles.

The reporter half (matcher + streaming worker) emits anonymised,
time-quantised segment tiles; this subsystem closes the loop the way the
reference ecosystem's companion datastore service did — turning tiles
into per-segment speed histograms and answering queries:

- :mod:`schema`     — histogram axes, composite keys, columnar batch
- :mod:`ingest`     — tile CSV / in-process ``Segment`` ingestion
- :mod:`aggregate`  — whole-batch searchsorted/add.at histogram kernel
- :mod:`store`      — append-only columnar partitions, atomic commits,
  mmap reads, compaction
- :mod:`query`      — mean / percentiles / coverage / transitions

:class:`LocalDatastore` is the one-stop facade the service's
``/histogram`` action, ``datastore_cli``, and the streaming worker's tee
all share.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .aggregate import Delta, aggregate, merge_deltas
from .ingest import ingest_dir, ingest_file, parse_tile_csv, scan_tiles
from .query import (
    DEFAULT_PERCENTILES,
    hours_for_range,
    parse_hours_spec,
    query_segment,
)
from .schema import ObservationBatch
from .store import HistogramStore


class LocalDatastore(HistogramStore):
    """A histogram store plus its query surface, rooted at a directory."""

    def ingest_segments(self, segments,
                        max_deltas: Optional[int] = None,
                        max_delta_bytes: Optional[int] = None,
                        ingest_key: Optional[str] = None) -> int:
        """Zero-serialisation path: aggregate culled ``Segment`` structs
        straight out of the anonymiser's flush, no CSV round trip. With
        compaction thresholds, the touched partitions are pressure-
        checked inline (the worker tee's automatic-compaction knobs).
        ``ingest_key`` — the flush identity the anonymiser passes its
        tee — makes the ingest idempotent (partition manifest ledger):
        a crash-replayed flush re-offers the same key and no-ops."""
        return self.ingest(ObservationBatch.from_segments(segments),
                           max_deltas=max_deltas,
                           max_delta_bytes=max_delta_bytes,
                           ingest_key=ingest_key)

    def ingest_csv(self, payload: str) -> int:
        return self.ingest(parse_tile_csv(payload))

    def ingest_dir(self, root: str, delete: bool = False,
                   limit: Optional[int] = None) -> dict:
        return ingest_dir(self, root, delete=delete, limit=limit)

    def query(self, segment_id: int,
              hours: Optional[Sequence[int]] = None,
              percentiles: Sequence[float] = DEFAULT_PERCENTILES,
              max_transitions: int = 32) -> dict:
        return query_segment(self, segment_id, hours=hours,
                             percentiles=percentiles,
                             max_transitions=max_transitions)


__all__ = [
    "Delta", "HistogramStore", "LocalDatastore", "ObservationBatch",
    "aggregate", "merge_deltas", "parse_tile_csv", "scan_tiles",
    "ingest_file", "ingest_dir", "query_segment", "hours_for_range",
    "parse_hours_spec", "DEFAULT_PERCENTILES",
]
