"""Query surface over the histogram store.

A segment query binary-searches each live segment file of the owning
partition for the segment's contiguous composite-key range (the keys are
sorted — schema.py), scatters the slices into a dense
``(168, N_SPEED_BINS)`` grid, and answers:

- observation count + exact mean speed (from the stored speed sums),
- interpolated percentiles from the binned CDF,
- the speed histogram itself (per requested hour set),
- hour-of-week coverage (distinct hours with data / hours asked),
- next-segment transition counts.

``hours`` restricts to a subset of the week (e.g. the morning peak);
:func:`hours_for_range` converts an epoch time range into that subset.

Batched serving (the multi-city dashboard path): :func:`query_many`
answers hundreds of segments in ONE sweep — segment ids group by owning
partition, each partition's live segment files are opened once (through
the handle LRU), and every file pays a single vectorised
``searchsorted`` over ALL requested key ranges instead of a re-open +
re-search per segment. :func:`query_bbox` resolves a lon/lat bounding
box to the graph tiles it covers (the same ``Tiles`` row/column math
the flush layout uses), enumerates the segments resident in those
partitions, and serves them through the same sweep.
:func:`query_segment` is the single-segment spelling of the same code
path, so batched and single answers are identical by construction.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.osmlr import tile_index, tile_level
from ..core.tiles import LEVEL_SIZES, TileHierarchy
from ..utils import metrics
from .schema import (
    CELLS_PER_SEGMENT,
    HOURS_PER_WEEK,
    N_SPEED_BINS,
    SPEED_BIN_KPH,
    hour_of_week,
)

DEFAULT_PERCENTILES = (25.0, 50.0, 75.0, 95.0)

#: bbox queries refuse to fan out past this many segments by default —
#: the truncation is EXPLICIT in the response ("truncated": true), never
#: a silently shorter list
DEFAULT_BBOX_MAX_SEGMENTS = 1024

#: per-sweep allocation bound: query_many processes a partition's id
#: list in chunks of this many segments (the dense grids cost ~70 KB a
#: segment — a huge request must cost time, never unbounded memory)
SWEEP_CHUNK_SEGMENTS = 1024


def hours_for_range(t0: int, t1: int) -> np.ndarray:
    """Hour-of-week subset covered by an epoch range [t0, t1)."""
    if t1 <= t0:
        return np.zeros(0, dtype=np.int64)
    n_hours = min((int(t1) - 1) // 3600 - int(t0) // 3600 + 1,
                  HOURS_PER_WEEK)
    first = hour_of_week(np.asarray([int(t0)]))[0]
    return np.unique((first + np.arange(n_hours)) % HOURS_PER_WEEK)


def parse_hours_spec(spec: Optional[str]):
    """Parse an hours argument: ``'7-9'`` (inclusive range) or ``'7,8,9'``.
    Shared by the CLI and the /histogram GET surface; range bounds are
    validated here, membership in [0, 167] by :func:`query_segment`."""
    if spec is None:
        return None
    if "-" in spec:
        lo, hi = spec.split("-", 1)
        lo, hi = int(lo), int(hi)
        if hi < lo:
            raise ValueError(f"empty hours range {spec!r}")
        return list(range(lo, hi + 1))
    return [int(h) for h in spec.split(",") if h]


def _percentiles(counts: np.ndarray, qs: Sequence[float]) -> dict:
    """Interpolated percentiles from one segment's per-bin counts (kph)
    — the n=1 spelling of :func:`_batch_percentiles`, so there is ONE
    interpolation implementation to keep correct."""
    counts = np.asarray(counts, dtype=np.int64).reshape(1, -1)
    totals = counts.sum(axis=1)
    vals = _batch_percentiles(counts, totals, qs)
    total = int(totals[0])
    return {f"p{q:g}": round(float(vals[q][0]), 3) if total else None
            for q in qs}


def _hour_selection(hours: Optional[Sequence[int]]) -> np.ndarray:
    if hours is not None:
        hour_sel = np.unique(np.asarray(list(hours), dtype=np.int64))
        if hour_sel.size and (hour_sel.min() < 0
                              or hour_sel.max() >= HOURS_PER_WEEK):
            raise ValueError("hours must be in [0, 167]")
        return hour_sel
    return np.arange(HOURS_PER_WEEK)


def _range_gather(starts: np.ndarray, stops: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Flatten N half-open index ranges into one fancy-index array:
    (indices, owner-of-each-index, total). The whole batch's slices of
    a segment file become ONE gather instead of N memmap slice reads."""
    lens = (stops - starts).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, 0
    shift = np.repeat(starts - np.concatenate(
        ([0], np.cumsum(lens)[:-1])), lens)
    idx = np.arange(total, dtype=np.int64) + shift
    owner = np.repeat(np.arange(starts.shape[0], dtype=np.int64), lens)
    return idx, owner, total


def _sweep_partition(store, level: int, index: int, seg_ids: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, list]:
    """One binary-searched sweep of a partition's live segment files
    for EVERY requested segment at once: per file, a single vectorised
    ``searchsorted`` over all key ranges, ONE fancy-index gather per
    column, and one batched scatter into the dense per-segment grids.
    Returns ``(grid_count (n, CELLS), grid_speed (n, CELLS),
    trans_parts [(owner, to, count)])``."""
    n = seg_ids.shape[0]
    los = seg_ids * CELLS_PER_SEGMENT
    grid_count = np.zeros((n, CELLS_PER_SEGMENT), dtype=np.int64)
    grid_speed = np.zeros((n, CELLS_PER_SEGMENT), dtype=np.float64)
    trans_parts: list = []
    for part in store.live_segments(level, index):
        i0s = np.searchsorted(part.hist_key, los, side="left")
        i1s = np.searchsorted(part.hist_key, los + CELLS_PER_SEGMENT,
                              side="left")
        idx, owner, total = _range_gather(i0s, i1s)
        if total:
            keys = np.asarray(part.hist_key[idx])
            cell = keys - los[owner]
            np.add.at(grid_count, (owner, cell),
                      np.asarray(part.hist_count[idx]))
            np.add.at(grid_speed, (owner, cell),
                      np.asarray(part.hist_speed_sum[idx]))
        j0s = np.searchsorted(part.trans_from, seg_ids, side="left")
        j1s = np.searchsorted(part.trans_from, seg_ids, side="right")
        tidx, towner, ttotal = _range_gather(j0s, j1s)
        if ttotal:
            trans_parts.append((towner,
                                np.asarray(part.trans_to[tidx]),
                                np.asarray(part.trans_count[tidx])))
    return grid_count, grid_speed, trans_parts


def _batch_percentiles(bin_counts: np.ndarray, totals: np.ndarray,
                       qs: Sequence[float]) -> Dict[float, np.ndarray]:
    """Vectorised percentile interpolation over (n, N_SPEED_BINS) bin
    counts — element-for-element the same arithmetic as
    :func:`_percentiles` (validated there), so the batched answer
    carries identical values."""
    for q in qs:
        if not 0.0 < float(q) <= 100.0:
            raise ValueError(f"percentile {q} out of range (0, 100]")
    cdf = np.cumsum(bin_counts, axis=1)
    lower = np.arange(N_SPEED_BINS) * SPEED_BIN_KPH
    rows = np.arange(bin_counts.shape[0])
    out = {}
    for q in qs:
        target = totals * (float(q) / 100.0)
        # rows' searchsorted(cdf, target, "left") == count of cdf < t
        b = np.minimum((cdf < target[:, None]).sum(axis=1),
                       N_SPEED_BINS - 1)
        prev = np.where(b > 0, cdf[rows, np.maximum(b - 1, 0)], 0)
        frac = (target - prev) / np.maximum(bin_counts[rows, b], 1)
        out[q] = lower[b] + frac * SPEED_BIN_KPH
    return out


def _assemble_results(seg_ids: np.ndarray, grid_count: np.ndarray,
                      grid_speed: np.ndarray, trans_parts: list,
                      hour_sel: np.ndarray,
                      percentiles: Sequence[float],
                      max_transitions: int) -> List[dict]:
    """Batched response assembly over one partition's swept grids: all
    grid reductions and percentile math run across segments at once;
    only dict building (and the transition ranking of segments that
    have any) stays per segment."""
    n = seg_ids.shape[0]
    sel_count = grid_count.reshape(
        n, HOURS_PER_WEEK, N_SPEED_BINS)[:, hour_sel, :]
    sel_speed = grid_speed.reshape(
        n, HOURS_PER_WEEK, N_SPEED_BINS)[:, hour_sel, :]
    bin_counts = sel_count.sum(axis=1)
    totals = bin_counts.sum(axis=1)
    speed_sums = sel_speed.sum(axis=(1, 2))
    hours_covered = (sel_count.sum(axis=2) > 0).sum(axis=1)
    pct = _batch_percentiles(bin_counts, totals, percentiles)

    # transitions: concatenate every part's gathered rows, then rank
    # per segment that has any (most segments in a bbox sweep have few)
    per_seg_trans: Dict[int, list] = {}
    if trans_parts:
        owner = np.concatenate([o for o, _t, _c in trans_parts])
        tos = np.concatenate([t for _o, t, _c in trans_parts])
        cnts = np.concatenate([c for _o, _t, c in trans_parts])
        # ONE sort groups every owner's rows (a per-owner boolean mask
        # would rescan the whole array once per owner); np.unique per
        # group re-sorts the slice, so row order within a group is
        # immaterial and the stable sort only keeps this deterministic
        order = np.argsort(owner, kind="stable")
        so, st, sc = owner[order], tos[order], cnts[order]
        uniq, starts = np.unique(so, return_index=True)
        ends = np.append(starts[1:], so.shape[0])
        for k, s, e in zip(uniq.tolist(), starts.tolist(),
                           ends.tolist()):
            uto, inv = np.unique(st[s:e], return_inverse=True)
            csum = np.zeros(uto.shape[0], dtype=np.int64)
            np.add.at(csum, inv, sc[s:e])
            rank = np.argsort(-csum, kind="stable")[:max_transitions]
            per_seg_trans[k] = [
                {"next_id": int(uto[j]), "count": int(csum[j])}
                for j in rank]

    hours_queried = int(hour_sel.size)
    out = []
    for k in range(n):
        seg = int(seg_ids[k])
        total = int(totals[k])
        # final rounding stays in Python round() — np.round's scaled
        # rint can differ in the last ulp, and the single-segment path
        # has always answered with Python rounding
        out.append({
            "segment_id": seg,
            "level": tile_level(seg),
            "tile_index": tile_index(seg),
            "count": total,
            "mean_kph": round(float(speed_sums[k] / total), 3)
            if total else None,
            "percentiles": {f"p{q:g}": round(float(pct[q][k]), 3)
                            if total else None
                            for q in percentiles},
            "histogram": {
                "bin_kph": SPEED_BIN_KPH,
                "counts": bin_counts[k].tolist(),
            },
            "hours_queried": hours_queried,
            "hours_covered": int(hours_covered[k]),
            "coverage": round(int(hours_covered[k]) / hours_queried, 4)
            if hours_queried else 0.0,
            "transitions": per_seg_trans.get(k, []),
        })
    return out


def query_many(store, segment_ids: Sequence[int],
               hours: Optional[Sequence[int]] = None,
               percentiles: Sequence[float] = DEFAULT_PERCENTILES,
               max_transitions: int = 32) -> List[dict]:
    """Answer MANY segments' histogram queries in one store sweep;
    results in input order (duplicates answered from the one sweep)."""
    with metrics.timer("datastore.query.many"):
        ids = [int(s) for s in segment_ids]
        metrics.count("datastore.query.batched_segments", len(ids))
        hour_sel = _hour_selection(hours)
        # group the unique ids by owning partition so each partition's
        # manifest read + handle fetch + per-file sweep happens ONCE
        by_part: Dict[Tuple[int, int], list] = {}
        for seg in dict.fromkeys(ids):  # unique, insertion-ordered
            by_part.setdefault((tile_level(seg), tile_index(seg)),
                               []).append(seg)
        results: Dict[int, dict] = {}
        for (level, index), segs in by_part.items():
            # chunk the sweep: the dense (n, 4200) grids cost ~70 KB a
            # segment, so an unbounded id list must not become one
            # unbounded allocation — each chunk's grids free before the
            # next (answers are per-segment, chunking cannot change
            # them)
            for i in range(0, len(segs), SWEEP_CHUNK_SEGMENTS):
                seg_arr = np.asarray(segs[i:i + SWEEP_CHUNK_SEGMENTS],
                                     dtype=np.int64)
                grid_count, grid_speed, trans_parts = _sweep_partition(
                    store, level, index, seg_arr)
                for res in _assemble_results(seg_arr, grid_count,
                                             grid_speed, trans_parts,
                                             hour_sel, percentiles,
                                             max_transitions):
                    results[res["segment_id"]] = res
        # duplicate ids get their OWN dicts (deep): an in-place
        # consumer mutating one answer must not contaminate its twins
        out, seen = [], set()
        for seg in ids:
            if seg in seen:
                out.append(copy.deepcopy(results[seg]))
            else:
                seen.add(seg)
                out.append(results[seg])
        return out


def query_segment(store, segment_id: int,
                  hours: Optional[Sequence[int]] = None,
                  percentiles: Sequence[float] = DEFAULT_PERCENTILES,
                  max_transitions: int = 32) -> dict:
    """Answer one segment's histogram query; see module docstring.

    This IS the batched path at n=1 (one shared sweep + assembler), so
    ``query_many`` stays answer-identical to per-segment queries by
    construction."""
    with metrics.timer("datastore.query"):
        segment_id = int(segment_id)
        seg_arr = np.asarray([segment_id], dtype=np.int64)
        hour_sel = _hour_selection(hours)
        grid_count, grid_speed, trans_parts = _sweep_partition(
            store, tile_level(segment_id), tile_index(segment_id),
            seg_arr)
        return _assemble_results(seg_arr, grid_count, grid_speed,
                                 trans_parts, hour_sel, percentiles,
                                 max_transitions)[0]


def bbox_tile_range(bbox: Sequence[float], level: int
                    ) -> Tuple[int, int, int, int, int]:
    """(row_lo, row_hi, col_lo, col_hi, ncolumns) — inclusive tile
    row/column bounds of ``level`` covering a (min_lon, min_lat,
    max_lon, max_lat) bbox, using the same row/column math (and edge
    clamps) as the tile layout (core/tiles.py). Out-of-world
    coordinates clamp to the tile system's bounds rather than erroring:
    a dashboard viewport may hang off the map edge."""
    minx, miny, maxx, maxy = (float(v) for v in bbox)
    if maxx < minx or maxy < miny:
        raise ValueError(f"empty bbox {list(bbox)!r}")
    if level not in LEVEL_SIZES:
        raise ValueError(f"level must be one of {sorted(LEVEL_SIZES)}")
    t = TileHierarchy().tiles(level)
    minx = min(max(minx, t.bbox.minx), t.bbox.maxx)
    maxx = min(max(maxx, t.bbox.minx), t.bbox.maxx)
    miny = min(max(miny, t.bbox.miny), t.bbox.maxy)
    maxy = min(max(maxy, t.bbox.miny), t.bbox.maxy)
    return t.row(miny), t.row(maxy), t.col(minx), t.col(maxx), t.ncolumns


def _bbox_ranges(bbox: Sequence[float], level: int) -> List[tuple]:
    """Antimeridian-aware :func:`bbox_tile_range`: a viewport with
    ``maxx`` STRICTLY below ``minx`` wraps ±180 (the reference
    semantics — ``core.tiles._split_antimeridian``, the same helper
    the tile enumeration uses) and yields one row/col range per split
    box. ``maxx == minx`` is a degenerate zero-width viewport, NOT a
    whole-world wrap (the split helper's ``>=`` test would read it as
    one)."""
    from ..core.tiles import _split_antimeridian
    minx, miny, maxx, maxy = (float(v) for v in bbox)
    if maxy < miny:
        raise ValueError(f"empty bbox {list(bbox)!r}")
    if maxx >= minx:
        return [bbox_tile_range([minx, miny, maxx, maxy], level)]
    return [bbox_tile_range([b.minx, b.miny, b.maxx, b.maxy], level)
            for b in _split_antimeridian([minx, miny, maxx, maxy])]


def bbox_partitions(bbox: Sequence[float], level: int) -> List[int]:
    """Graph tile indices of ``level`` intersecting a lon/lat bbox
    (the dense enumeration — tests and small viewports; the query path
    instead intersects the row/col RANGE with on-disk partitions so a
    whole-world bbox never enumerates a million tile ids)."""
    out: List[int] = []
    for r0, r1, c0, c1, ncols in _bbox_ranges(bbox, level):
        out.extend(r * ncols + c
                   for r in range(r0, r1 + 1)
                   for c in range(c0, c1 + 1))
    return sorted(set(out))


def resident_segments(store, level: int, index: int) -> np.ndarray:
    """Distinct segment ids with histogram cells in one partition
    (cached in the store keyed by manifest content — store.py)."""
    return store.resident_segments(level, index)


def query_bbox(store, bbox: Sequence[float], level: int,
               hours: Optional[Sequence[int]] = None,
               percentiles: Sequence[float] = DEFAULT_PERCENTILES,
               max_transitions: int = 32,
               max_segments: int = DEFAULT_BBOX_MAX_SEGMENTS) -> dict:
    """Every resident segment of ``level`` inside a lon/lat bbox, served
    through the :func:`query_many` sweep. The segment list is bounded by
    ``max_segments`` with an explicit ``truncated`` flag."""
    with metrics.timer("datastore.query.bbox"):
        ranges = _bbox_ranges(bbox, level)
        seg_lists = []
        # intersect the bbox's row/col RANGE(s) with what is on disk:
        # O(resident partitions), never O(bbox tiles)
        for lvl, index in store.partitions():
            if lvl != level:
                continue
            if any(r0 <= index // ncols <= r1
                   and c0 <= index % ncols <= c1
                   for r0, r1, c0, c1, ncols in ranges):
                seg_lists.append(resident_segments(store, level, index))
        ids = (np.unique(np.concatenate(seg_lists)).tolist()
               if seg_lists else [])
        truncated = len(ids) > max_segments
        if truncated:
            ids = ids[:max_segments]
        return {
            "bbox": [float(v) for v in bbox],
            "level": int(level),
            "n_segments": len(ids),
            "truncated": truncated,
            "segments": query_many(store, ids, hours=hours,
                                   percentiles=percentiles,
                                   max_transitions=max_transitions),
        }


__all__ = ["query_segment", "query_many", "query_bbox",
           "bbox_partitions", "bbox_tile_range",
           "resident_segments", "hours_for_range",
           "parse_hours_spec", "DEFAULT_PERCENTILES",
           "DEFAULT_BBOX_MAX_SEGMENTS"]
