"""Query surface over the histogram store.

A segment query binary-searches each live segment file of the owning
partition for the segment's contiguous composite-key range (the keys are
sorted — schema.py), scatters the slices into a dense
``(168, N_SPEED_BINS)`` grid, and answers:

- observation count + exact mean speed (from the stored speed sums),
- interpolated percentiles from the binned CDF,
- the speed histogram itself (per requested hour set),
- hour-of-week coverage (distinct hours with data / hours asked),
- next-segment transition counts.

``hours`` restricts to a subset of the week (e.g. the morning peak);
:func:`hours_for_range` converts an epoch time range into that subset.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.osmlr import tile_index, tile_level
from ..utils import metrics
from .schema import (
    CELLS_PER_SEGMENT,
    HOURS_PER_WEEK,
    N_SPEED_BINS,
    SPEED_BIN_KPH,
    hour_of_week,
    segment_key_range,
)

DEFAULT_PERCENTILES = (25.0, 50.0, 75.0, 95.0)


def hours_for_range(t0: int, t1: int) -> np.ndarray:
    """Hour-of-week subset covered by an epoch range [t0, t1)."""
    if t1 <= t0:
        return np.zeros(0, dtype=np.int64)
    n_hours = min((int(t1) - 1) // 3600 - int(t0) // 3600 + 1,
                  HOURS_PER_WEEK)
    first = hour_of_week(np.asarray([int(t0)]))[0]
    return np.unique((first + np.arange(n_hours)) % HOURS_PER_WEEK)


def parse_hours_spec(spec: Optional[str]):
    """Parse an hours argument: ``'7-9'`` (inclusive range) or ``'7,8,9'``.
    Shared by the CLI and the /histogram GET surface; range bounds are
    validated here, membership in [0, 167] by :func:`query_segment`."""
    if spec is None:
        return None
    if "-" in spec:
        lo, hi = spec.split("-", 1)
        lo, hi = int(lo), int(hi)
        if hi < lo:
            raise ValueError(f"empty hours range {spec!r}")
        return list(range(lo, hi + 1))
    return [int(h) for h in spec.split(",") if h]


def _percentiles(counts: np.ndarray, qs: Sequence[float]) -> dict:
    """Interpolated percentiles from per-bin counts (kph)."""
    for q in qs:
        if not 0.0 < float(q) <= 100.0:
            raise ValueError(f"percentile {q} out of range (0, 100]")
    total = counts.sum()
    out = {}
    if total == 0:
        for q in qs:
            out[f"p{q:g}"] = None
        return out
    cdf = np.cumsum(counts)
    lower = np.arange(N_SPEED_BINS) * SPEED_BIN_KPH
    for q in qs:
        target = total * (float(q) / 100.0)
        b = int(np.searchsorted(cdf, target, side="left"))
        b = min(b, N_SPEED_BINS - 1)
        prev = cdf[b - 1] if b else 0
        frac = (target - prev) / max(counts[b], 1)
        out[f"p{q:g}"] = round(float(lower[b] + frac * SPEED_BIN_KPH), 3)
    return out


def query_segment(store, segment_id: int,
                  hours: Optional[Sequence[int]] = None,
                  percentiles: Sequence[float] = DEFAULT_PERCENTILES,
                  max_transitions: int = 32) -> dict:
    """Answer one segment's histogram query; see module docstring."""
    with metrics.timer("datastore.query"):
        segment_id = int(segment_id)
        level = tile_level(segment_id)
        index = tile_index(segment_id)
        lo, hi = segment_key_range(segment_id)
        grid_count = np.zeros(CELLS_PER_SEGMENT, dtype=np.int64)
        grid_speed = np.zeros(CELLS_PER_SEGMENT, dtype=np.float64)
        trans_to_parts = []
        trans_count_parts = []
        for part in store.live_segments(level, index):
            i0 = int(np.searchsorted(part.hist_key, lo, side="left"))
            i1 = int(np.searchsorted(part.hist_key, hi, side="left"))
            if i1 > i0:
                cell = np.asarray(part.hist_key[i0:i1]) - lo
                np.add.at(grid_count, cell, part.hist_count[i0:i1])
                np.add.at(grid_speed, cell, part.hist_speed_sum[i0:i1])
            j0 = int(np.searchsorted(part.trans_from, segment_id, "left"))
            j1 = int(np.searchsorted(part.trans_from, segment_id, "right"))
            if j1 > j0:
                trans_to_parts.append(np.asarray(part.trans_to[j0:j1]))
                trans_count_parts.append(np.asarray(part.trans_count[j0:j1]))

        grid_count = grid_count.reshape(HOURS_PER_WEEK, N_SPEED_BINS)
        grid_speed = grid_speed.reshape(HOURS_PER_WEEK, N_SPEED_BINS)
        if hours is not None:
            hour_sel = np.unique(np.asarray(list(hours), dtype=np.int64))
            if hour_sel.size and (hour_sel.min() < 0
                                  or hour_sel.max() >= HOURS_PER_WEEK):
                raise ValueError("hours must be in [0, 167]")
        else:
            hour_sel = np.arange(HOURS_PER_WEEK)
        sel_count = grid_count[hour_sel]
        sel_speed = grid_speed[hour_sel]

        bin_counts = sel_count.sum(axis=0)
        total = int(bin_counts.sum())
        mean = round(float(sel_speed.sum() / total), 3) if total else None
        hours_covered = int((sel_count.sum(axis=1) > 0).sum())

        if trans_to_parts:
            to_all = np.concatenate(trans_to_parts)
            cnt_all = np.concatenate(trans_count_parts)
            uto, inv = np.unique(to_all, return_inverse=True)
            csum = np.zeros(uto.shape[0], dtype=np.int64)
            np.add.at(csum, inv, cnt_all)
            order = np.argsort(-csum, kind="stable")[:max_transitions]
            transitions = [
                {"next_id": int(uto[k]), "count": int(csum[k])}
                for k in order]
        else:
            transitions = []

        return {
            "segment_id": segment_id,
            "level": level,
            "tile_index": index,
            "count": total,
            "mean_kph": mean,
            "percentiles": _percentiles(bin_counts, percentiles),
            "histogram": {
                "bin_kph": SPEED_BIN_KPH,
                "counts": bin_counts.tolist(),
            },
            "hours_queried": int(hour_sel.size),
            "hours_covered": hours_covered,
            "coverage": round(hours_covered / hour_sel.size, 4)
            if hour_sel.size else 0.0,
            "transitions": transitions,
        }


__all__ = ["query_segment", "hours_for_range", "parse_hours_spec",
           "DEFAULT_PERCENTILES"]
