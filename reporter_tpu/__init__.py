"""reporter_tpu — a TPU-native GPS probe map-matching and traffic-reporting framework.

A ground-up rebuild of the capabilities of Open Traffic Reporter
(reference: /root/reference, cuulee/reporter): ingest raw GPS probe data,
map-match traces to OSMLR traffic segments with an HMM matcher, convert matched
segments into speed reports, anonymise them behind a privacy threshold, and
flush time-quantised geographic tiles to a datastore.

Where the reference runs a one-trace-at-a-time C++ Meili matcher behind an HTTP
service (reference: py/reporter_service.py), this framework runs a *batched*
JAX/XLA HMM: host-side candidate lookup feeds fixed-width tensors to a vmapped
Viterbi decode on TPU, thousands of padded traces per device step.

Layout:
  core/      — value types, OSMLR id math, tile hierarchy, geodesy
  graph/     — road network, spatial index, candidate extraction (host side)
  matcher/   — JAX HMM (emission/transition/Viterbi), segment assembly, Match API
  service/   — /report HTTP service with micro-batching, report() semantics
  streaming/ — formatter, per-uuid batcher, anonymiser, broker adapters
  pipeline/  — batched (historical) 3-stage pipeline
  parallel/  — device mesh + sharding of the batched matcher
  ops/       — Pallas TPU kernels for the hot ops
  native/    — C++ host runtime (spatial index, route distances) via ctypes
"""

__version__ = "0.1.0"
