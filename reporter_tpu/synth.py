"""Synthetic road networks and ground-truth GPS traces.

The reference generates test traces by routing against a live Valhalla server
and resampling the shape at per-edge speed with correlated Gaussian noise
(reference: py/generate_test_trace.py:35-149). This module is the equivalent
harness with no external dependencies: it builds a deterministic grid city
with OSMLR-associated edges, routes between random nodes, and synthesises
noisy per-second probes — returning both the request JSON the service expects
and the ground-truth edge/segment sequence for accuracy scoring.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from .core.geo import local_meters_projection
from .core.osmlr import make_segment_id
from .core.tiles import TileHierarchy
from .graph.network import RoadNetwork
from .graph.route import shortest_path_edges

# Manila-ish anchor so tile ids look like the reference deployment's
DEFAULT_LAT0 = 14.60
DEFAULT_LON0 = 120.98


def build_grid_city(rows: int = 20, cols: int = 20, spacing_m: float = 200.0,
                    lat0: float = DEFAULT_LAT0, lon0: float = DEFAULT_LON0,
                    edges_per_segment: int = 3, seed: int = 0,
                    service_road_fraction: float = 0.05,
                    internal_fraction: float = 0.02) -> RoadNetwork:
    """A grid city: ``rows x cols`` intersections ``spacing_m`` apart.

    Streets get hierarchy levels the way real OSMLR tiles do: every 8th
    row/col is a level-0 highway (80 kph), every 4th a level-1 arterial
    (60 kph), the rest level-2 locals (40 kph). Consecutive same-direction
    edges chain into OSMLR segments of ``edges_per_segment`` blocks whose
    tile index is the true geographic tile of the segment midpoint.
    A few edges are left unassociated (service roads) or marked internal
    (turn channels), which the report path must skip / merge across
    (reference: py/reporter_service.py:109-110,159-162).
    """
    rng = np.random.default_rng(seed)
    _, to_ll = local_meters_projection(lat0, lon0)

    xs = (np.arange(cols) - (cols - 1) / 2.0) * spacing_m
    ys = (np.arange(rows) - (rows - 1) / 2.0) * spacing_m
    gx, gy = np.meshgrid(xs, ys)  # (rows, cols)
    node_lat, node_lon = to_ll(gx.ravel(), gy.ravel())

    def node_id(r: int, c: int) -> int:
        return r * cols + c

    def street_level(index: int) -> int:
        if index % 8 == 0:
            return 0
        if index % 4 == 0:
            return 1
        return 2

    speed_for_level = {0: 80.0, 1: 60.0, 2: 40.0}

    starts: List[int] = []
    ends: List[int] = []
    lengths: List[float] = []
    speeds: List[float] = []
    seg_ids: List[int] = []
    seg_offsets: List[float] = []
    internal: List[bool] = []

    hierarchy = TileHierarchy()
    seg_counters = {}  # (level, tile_idx) -> next segment index
    segment_length_m = {}

    def add_run(node_seq: List[int], level: int):
        """One directed run of edges along a street, chained into segments."""
        speed = speed_for_level[level]
        for chunk_start in range(0, len(node_seq) - 1, edges_per_segment):
            chunk = node_seq[chunk_start:chunk_start + edges_per_segment + 1]
            if len(chunk) < 2:
                break
            # geographic tile of the chunk midpoint names the segment's tile
            mid = chunk[len(chunk) // 2]
            tiles = hierarchy.tiles(level)
            tile_idx = tiles.tile_id(float(node_lat[mid]), float(node_lon[mid]))
            key = (level, tile_idx)
            seg_idx = seg_counters.get(key, 0)
            seg_counters[key] = seg_idx + 1
            sid = make_segment_id(level, tile_idx, seg_idx)

            offset = 0.0
            for a, b in zip(chunk[:-1], chunk[1:]):
                is_service = rng.random() < service_road_fraction
                is_internal = (not is_service) and rng.random() < internal_fraction
                starts.append(a)
                ends.append(b)
                lengths.append(spacing_m)
                speeds.append(speed)
                if is_service or is_internal:
                    seg_ids.append(-1)
                    seg_offsets.append(0.0)
                else:
                    seg_ids.append(sid)
                    seg_offsets.append(offset)
                internal.append(is_internal)
                offset += spacing_m
            segment_length_m[sid] = offset

    # horizontal streets (both directions), vertical streets (both directions)
    for r in range(rows):
        level = street_level(r)
        seq = [node_id(r, c) for c in range(cols)]
        add_run(seq, level)
        add_run(seq[::-1], level)
    for c in range(cols):
        level = street_level(c)
        seq = [node_id(r, c) for r in range(rows)]
        add_run(seq, level)
        add_run(seq[::-1], level)

    return RoadNetwork(
        node_lat=np.asarray(node_lat, dtype=np.float64),
        node_lon=np.asarray(node_lon, dtype=np.float64),
        edge_start=np.asarray(starts, dtype=np.int32),
        edge_end=np.asarray(ends, dtype=np.int32),
        edge_length_m=np.asarray(lengths, dtype=np.float32),
        edge_speed_kph=np.asarray(speeds, dtype=np.float32),
        edge_segment_id=np.asarray(seg_ids, dtype=np.int64),
        edge_segment_offset_m=np.asarray(seg_offsets, dtype=np.float32),
        edge_internal=np.asarray(internal, dtype=bool),
        segment_length_m=segment_length_m,
    )


@dataclass
class SyntheticTrace:
    """A generated probe trace plus its ground truth."""
    uuid: str
    points: List[dict]          # [{lat, lon, time, accuracy}, ...]
    edge_path: List[int]        # ground-truth edge ids traversed
    point_edges: List[int]      # ground-truth edge id at each sample
    point_offsets: List[float]  # along-edge offset at each sample

    def request_json(self, mode: str = "auto",
                     report_levels=(0, 1), transition_levels=(0, 1)) -> dict:
        """The /report request body (reference: Batch.java:56-66)."""
        return {
            "uuid": self.uuid,
            "trace": self.points,
            "match_options": {
                "mode": mode,
                "report_levels": list(report_levels),
                "transition_levels": list(transition_levels),
            },
        }

    def truth_segments(self, net: RoadNetwork) -> List[int]:
        """Ordered distinct OSMLR segment ids along the ground-truth path."""
        out: List[int] = []
        for e in self.edge_path:
            sid = int(net.edge_segment_id[e])
            if sid >= 0 and (not out or out[-1] != sid):
                out.append(sid)
        return out

    def truth_complete_segments(self, net: RoadNetwork) -> List[int]:
        """Segment ids the ground-truth path traversed END TO END — the
        set a correct matcher must report with a real length (reference
        README.md "Reporter Output": length=-1 marks partial traversal).
        A truth segment counts only when the path covered it from offset
        0 through its full length; a route that turns onto or off a
        multi-block segment mid-way did NOT traverse it completely, even
        mid-route."""
        out: List[int] = []
        run_start_off = None
        prev_sid = None
        prev_end = 0.0
        for e in self.edge_path:
            sid = int(net.edge_segment_id[e])
            off = float(net.edge_segment_offset_m[e])
            if sid != prev_sid:
                if prev_sid is not None and prev_sid >= 0 \
                        and run_start_off is not None \
                        and run_start_off <= 1e-3 and prev_end >= \
                        net.segment_length_m.get(prev_sid, float("inf")) - 1e-3:
                    out.append(prev_sid)
                run_start_off = off if sid >= 0 else None
                prev_sid = sid
            prev_end = off + float(net.edge_length_m[e])
        if prev_sid is not None and prev_sid >= 0 \
                and run_start_off is not None and run_start_off <= 1e-3 \
                and prev_end >= net.segment_length_m.get(
                    prev_sid, float("inf")) - 1e-3:
            out.append(prev_sid)
        return out


def generate_trace(net: RoadNetwork, uuid: str, rng: np.random.Generator,
                   noise_m: float = 5.0, sample_period_s: float = 1.0,
                   start_time: int = 1_500_000_000,
                   min_route_edges: int = 6,
                   max_route_edges: int = 60) -> Optional[SyntheticTrace]:
    """Route between random nodes and synthesise noisy per-second probes.

    The vehicle advances along the edge path at each edge's speed; a probe is
    emitted every ``sample_period_s`` with isotropic Gaussian position noise
    of ``noise_m`` meters std (the reference's correlated-walk noise model at
    generate_test_trace.py:77-92 is approximated as iid; accuracy is the
    95th-percentile circle like generate_test_trace.py:40).
    """
    src, dst = rng.integers(0, net.num_nodes, size=2)
    if src == dst:
        return None
    path = shortest_path_edges(net, int(src), int(dst))
    if path is None or not (min_route_edges <= len(path)):
        return None
    path = path[:max_route_edges]

    nx, ny = net.node_xy()
    _, to_ll = net.projection()

    accuracy = int(math.ceil(min(100.0, 1.96 * max(1.0, noise_m))))
    points: List[dict] = []
    point_edges: List[int] = []
    point_offsets: List[float] = []

    t = 0.0
    next_sample = 0.0
    for e in path:
        length = float(net.edge_length_m[e])
        mps = float(net.edge_speed_kph[e]) * 1000.0 / 3600.0
        duration = length / mps
        ax, ay = nx[net.edge_start[e]], ny[net.edge_start[e]]
        bx, by = nx[net.edge_end[e]], ny[net.edge_end[e]]
        while next_sample < t + duration:
            frac = (next_sample - t) / duration
            x = ax + frac * (bx - ax) + rng.normal(0.0, noise_m)
            y = ay + frac * (by - ay) + rng.normal(0.0, noise_m)
            lat, lon = to_ll(x, y)
            points.append({
                "lat": round(float(lat), 6),
                "lon": round(float(lon), 6),
                "time": int(start_time + round(next_sample)),
                "accuracy": accuracy,
            })
            point_edges.append(e)
            point_offsets.append(frac * length)
            next_sample += sample_period_s
        t += duration

    if len(points) < 2:
        return None
    return SyntheticTrace(uuid=uuid, points=points, edge_path=path,
                          point_edges=point_edges, point_offsets=point_offsets)
