"""Streaming backpressure: the batcher slows down instead of spooling.

Before ISSUE 15 the streaming side had exactly one response to a slow
or failing matcher: keep accepting points, requeue failed submits, and
grow — the in-memory session store, the pending-report set, and
eventually the dead-letter spool all absorbed the overload silently.
This module is the governor that turns sustained submit pressure into
*flow control*:

- **Sensors.** :meth:`BackpressureGovernor.note_flush` feeds every
  batched submit's wall time into an EWMA of per-trace submit latency,
  and tracks the *requeue depth* — how many sessions currently carry a
  failed-submit retry (the batcher maintains the live set; its size is
  O(1) to read).

- **Slow offer acceptance.** When the submit-latency EWMA crosses
  ``latency_high_s`` or the requeue depth crosses ``depth_high``, the
  worker's offer loop sleeps :meth:`offer_delay` per message — a
  BOUNDED block (``max_delay_s``) that propagates the slowdown to the
  upstream consumer (a Kafka poll loop naturally lags; a replay reads
  slower) instead of letting memory absorb it. The delay scales with
  how far past the threshold the sensor sits, so mild pressure costs
  microseconds and a dead matcher costs the full bound.

- **Shed, accounted.** Past ``SHED_FACTOR`` times either threshold the
  governor declares :meth:`should_shed`: sessions whose batches cross
  the report thresholds dead-letter their trace JSON immediately
  (``backpressure.shed``; the PR 9 drainer replays them when the
  matcher recovers) instead of joining a pending set that can only
  grow. Nothing is dropped silently — the spool is the bounded,
  replayable parking lot it was built to be.

``REPORTER_TPU_BACKPRESSURE=0`` disables the governor (the pre-ISSUE-15
spool-and-hope behaviour); the default thresholds are conservative
enough that a healthy matcher never trips them.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ..utils import metrics
from ..utils.runtime import _env_float

ENV_BACKPRESSURE = "REPORTER_TPU_BACKPRESSURE"

#: per-trace submit-latency EWMA above which offers slow down; a
#: batched in-process submit runs well under 10 ms/trace on any box
#: this serves from, so 1 s/trace is unambiguous distress
DEFAULT_LATENCY_HIGH_S = 1.0
#: sessions carrying a failed-submit retry before offers slow down
DEFAULT_DEPTH_HIGH = 32
#: hard bound on the per-offer block — flow control, not a stall
DEFAULT_MAX_DELAY_S = 0.05
#: sensor multiple past the slow-down threshold at which report-ready
#: sessions dead-letter instead of joining the pending set
SHED_FACTOR = 4.0
_EWMA_ALPHA = 0.3


class BackpressureGovernor:
    """Submit-pressure sensors -> bounded offer delay + shed verdicts.

    Single-threaded by design, like the batcher that owns it: every
    method runs on the stream-processing thread, so there is no lock —
    the same discipline as :class:`..streaming.batcher.PointBatcher`.
    """

    def __init__(self,
                 latency_high_s: Optional[float] = None,
                 depth_high: Optional[int] = None,
                 max_delay_s: float = DEFAULT_MAX_DELAY_S,
                 clock: Callable[[], float] = time.monotonic):
        import os
        self.enabled = os.environ.get(ENV_BACKPRESSURE, "").strip() \
            .lower() not in ("0", "off", "false", "no")
        self.latency_high_s = latency_high_s \
            if latency_high_s is not None \
            else _env_float("REPORTER_TPU_BACKPRESSURE_LATENCY_S",
                            DEFAULT_LATENCY_HIGH_S)
        self.depth_high = depth_high if depth_high is not None \
            else DEFAULT_DEPTH_HIGH
        self.max_delay_s = max_delay_s
        self.clock = clock
        self.ewma_s: Optional[float] = None  # per-trace submit latency
        self.requeue_depth = 0
        self.flushes = 0
        self.failed_flushes = 0

    # -- sensors ----------------------------------------------------------
    def note_flush(self, n_traces: int, elapsed_s: float,
                   failures: int, requeue_depth: int) -> None:
        """One batched submit's outcome: wall time over ``n_traces``
        (EWMA input), how many traces failed, and the live requeue
        depth after the batcher's retry bookkeeping."""
        self.flushes += 1
        if failures:
            self.failed_flushes += 1
        self.requeue_depth = int(requeue_depth)
        if n_traces > 0 and elapsed_s >= 0.0:
            per_trace = elapsed_s / n_traces
            self.ewma_s = per_trace if self.ewma_s is None else \
                (1.0 - _EWMA_ALPHA) * self.ewma_s \
                + _EWMA_ALPHA * per_trace

    def _pressure(self) -> float:
        """How far past the slow-down thresholds the worst sensor sits
        (1.0 = at threshold; <1 = calm)."""
        ratio = 0.0
        if self.ewma_s is not None and self.latency_high_s > 0:
            ratio = self.ewma_s / self.latency_high_s
        if self.depth_high > 0:
            ratio = max(ratio, self.requeue_depth / self.depth_high)
        return ratio

    # -- verdicts ---------------------------------------------------------
    def offer_delay(self) -> float:
        """Seconds the offer loop should block before accepting the
        next message: 0 when calm, scaling linearly to ``max_delay_s``
        at ``SHED_FACTOR`` times the threshold."""
        if not self.enabled:
            return 0.0
        ratio = self._pressure()
        if ratio <= 1.0:
            return 0.0
        frac = min((ratio - 1.0) / (SHED_FACTOR - 1.0), 1.0)
        return frac * self.max_delay_s

    def should_shed(self) -> bool:
        """Whether report-ready sessions should dead-letter instead of
        queueing: the bounded block was not enough."""
        return self.enabled and self._pressure() >= SHED_FACTOR

    def snapshot(self) -> dict:
        return {
            "enabled": self.enabled,
            "submit_ewma_ms": round(self.ewma_s * 1000.0, 3)
            if self.ewma_s is not None else None,
            "latency_high_ms": round(self.latency_high_s * 1000.0, 1),
            "requeue_depth": self.requeue_depth,
            "depth_high": self.depth_high,
            "pressure": round(self._pressure(), 4),
            "delaying": self.offer_delay() > 0.0,
            "shedding": self.should_shed(),
            "delays": metrics.default.counter("backpressure.delays"),
            "shed": metrics.default.counter("backpressure.shed"),
        }


__all__ = ["BackpressureGovernor", "ENV_BACKPRESSURE", "SHED_FACTOR"]
