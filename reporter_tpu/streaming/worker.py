"""The streaming worker: raw probe stream -> anonymised traffic tiles.

Single-process topology mirroring the reference's Kafka Streams worker
(reference: Reporter.java:21-39 topology diagram, :138-194 wiring):

    raw -> [Formatter] -> (uuid, Point) -> [PointBatcher] -> (key, Segment)
        -> [Anonymiser] -> tiles -> file / http / s3

with the matcher reached either in-process (default — micro-batched onto
the TPU via the service dispatcher) or over HTTP for split deployments
(the reference's only mode, Batch.java:66-72).

CLI options named after the reference's (Reporter.java:43-136):
  --formatter/-f   one-string formatter config
  --reporter-url/-u  http endpoint; omit for in-process matching
  --mode/-m --reports/-r --transitions/-x
  --privacy/-p --quantisation/-q --flush-interval/-i
  --source/-s --output-location/-o --duration/-d
plus --input (flat file / '-' for stdin replay) or --bootstrap/-b with
--topics/-t for Kafka.
"""
from __future__ import annotations

import argparse
import contextlib
import json
import logging
import os
import sys
import time
from typing import Callable, Iterable, Optional

from ..obs import flightrec, profiler
from ..obs import trace as obs_trace
from ..utils import faults, metrics
from ..utils import http as http_egress
from ..utils import spool as spool_mod
from .anonymiser import Anonymiser, TileSink
from .batcher import PointBatcher, SESSION_GAP_MS
from .drainer import DeadLetterDrainer, replay_knobs
from .formatter import Formatter

logger = logging.getLogger("reporter_tpu.streaming")


def _pressure_level() -> dict:
    """The process-wide degradation-ladder state for the heartbeat
    (lazy import: a pure-streaming process without the serving tier
    loaded reports the quiescent shape without importing it)."""
    import sys
    admission = sys.modules.get("reporter_tpu.service.admission")
    if admission is None:
        return {"level": 0, "state": "normal", "transitions": 0}
    return admission.pressure_snapshot()


def http_submitter(url: str) -> Callable[[dict], Optional[dict]]:
    """POST the trace to a matcher service, with the reference's retry
    policy; returns parsed JSON or None (reference: HttpClient.java:65-103).
    """
    def submit(trace: dict) -> Optional[dict]:
        body = json.dumps(trace, separators=(",", ":"))
        text = http_egress.post(url, body, content_type="application/json")
        if text is None:
            return None
        try:
            return json.loads(text)
        except json.JSONDecodeError as e:
            logger.error("unparseable matcher response from %s: %s", url, e)
            return None
    return submit


def inproc_submitter(service) -> Callable[[dict], Optional[dict]]:
    """Use a ReporterService in this process — the TPU-native default."""
    def submit(trace: dict) -> Optional[dict]:
        code, body = service.handle(trace)
        if code != 200:
            logger.error("in-process match failed (%d): %s", code, body)
            return None
        # the native wire path hands back a memoryview of the chunk
        # buffer (zero-copy for sockets); json.loads wants bytes/str
        return json.loads(bytes(body) if isinstance(body, memoryview)
                          else body)
    return submit


class StreamWorker:
    """Wires formatter -> batcher -> anonymiser and drives punctuation."""

    def __init__(self, formatter: Formatter,
                 submit: Callable[[dict], Optional[dict]],
                 anonymiser: Anonymiser,
                 mode: str = "auto", reports: str = "0,1",
                 transitions: str = "0,1",
                 flush_interval_s: float = 3600.0,
                 session_gap_ms: int = SESSION_GAP_MS,
                 clock=time.time,
                 state=None,
                 uuid_filter: Optional[Callable[[str], bool]] = None,
                 submit_many=None,
                 report_flush_interval_s: float = 1.0,
                 trace_deadletter: Optional[str] = None,
                 circuit_probe: Optional[Callable[[], str]] = None,
                 degraded_probe: Optional[Callable[[], list]] = None,
                 incremental_probe: Optional[
                     Callable[[], Optional[dict]]] = None,
                 on_evict: Optional[Callable[[str], None]] = None,
                 datastore=None, compactor=None,
                 map_version: Optional[str] = None):
        self.formatter = formatter
        # content-derived identity of the graph this worker matches
        # against (graph/version.py), surfaced in the heartbeat so a
        # fleet's log pipeline can see which map build each worker is
        # on across a swap; None when the owner wired no graph probe
        self.map_version = map_version
        # multi-host: predicate deciding which uuids this worker owns
        # (parallel.multihost — the Kafka keyed-partition contract when the
        # input stream is not already partitioned); None = own everything
        self.uuid_filter = uuid_filter
        self.skipped_other_host = 0
        self.anonymiser = anonymiser
        spool = getattr(getattr(anonymiser, "sink", None),
                        "deadletter", None)
        if trace_deadletter is None:
            # default next to the tile dead-letter spool, dot-prefixed so
            # `datastore ingest` over that spool never mistakes a trace
            # JSON for a tile CSV (ingest.scan_tiles skips it by name);
            # stub sinks without a spool leave it off (log-and-drop)
            if spool:
                trace_deadletter = os.path.join(spool, ".traces")
        # the flight recorder dumps its postmortems next to the spools
        # (same layout contract: dot-prefixed, skipped by scan_tiles);
        # an explicit REPORTER_TPU_FLIGHTREC wins inside set_dump_dir
        if spool:
            flightrec.set_dump_dir(os.path.join(spool, ".flightrec"))
        # register the spool roots so the matcher's poisoned-trace
        # quarantine and /health's backlog gauges find them without
        # plumbing (utils.spool, module-level like the flight recorder).
        # Last-writer-wins: a multi-worker process (tools/bigreplay.py)
        # must wire matcher.quarantine_spool per matcher instead of
        # relying on these globals, and the heartbeat below reads the
        # per-instance roots, not the globals
        self._tile_spool = spool or None
        self._trace_spool = trace_deadletter
        spool_mod.set_tile_dir(spool or None)
        spool_mod.set_trace_dir(trace_deadletter)
        # automated dead-letter replay (ISSUE 9): paced on THIS thread
        # via maybe_punctuate (the anonymiser/batcher have no locks),
        # re-submitting spooled traces through the live submit path and
        # re-egressing spooled tiles through the live sink.
        # REPORTER_TPU_REPLAY_INTERVAL_S=0 (default) disables.
        replay_interval, replay_attempts = replay_knobs()
        self.drainer = None
        if replay_interval > 0 and (spool or trace_deadletter):
            self.drainer = DeadLetterDrainer(
                spool or None, trace_root=trace_deadletter,
                submit=submit,
                forward=lambda key, seg: self.anonymiser.process(key, seg),
                sink=getattr(anonymiser, "sink", None),
                datastore=datastore,
                interval_s=replay_interval,
                max_attempts=replay_attempts)
        self.batcher = PointBatcher(
            submit, lambda key, seg: self.anonymiser.process(key, seg),
            mode=mode, report_on=reports, transition_on=transitions,
            session_gap_ms=session_gap_ms, submit_many=submit_many,
            deadletter_dir=trace_deadletter, on_evict=on_evict)
        self.flush_interval_s = flush_interval_s
        self.session_gap_ms = session_gap_ms
        self.clock = clock
        self.processed = 0
        self.parse_failures = 0
        self._last_flush = clock()
        self._last_evict = clock()
        # wall-clock bound on how long a threshold-crossed session may sit
        # in the batcher's pending set before a batched flush: keeps live
        # report latency near the reference's immediate-fire behavior
        # while a fast replay still accumulates whole device batches
        self.report_flush_interval_s = report_flush_interval_s
        self._last_report_flush = clock()
        # structured heartbeat: the reference logged a bare counter every
        # 10k messages (KeyedFormattingProcessor.java:36-38); this one is
        # wall-clock paced (monotonic — independent of injected replay
        # clocks) and single-line JSON so a log pipeline can chart it.
        # 0 disables.
        from ..utils.runtime import _env_float
        self.heartbeat_s = _env_float("REPORTER_TPU_HEARTBEAT_S", 0.0)
        # circuit-state probe for the heartbeat (in-process deployments
        # pass the matcher's breaker; HTTP splits have none to read);
        # degraded_probe names the OPEN domains (matcher.open_domains)
        self.circuit_probe = circuit_probe
        self.degraded_probe = degraded_probe
        # carried-state gauge for the incremental matcher path
        # (IncrementalTable.gauge(); None until the table exists / on
        # HTTP split deployments, which have no in-process matcher)
        self.incremental_probe = incremental_probe
        self._hb_last = time.monotonic()
        self._hb_processed = 0
        # background compaction (datastore/compactor.py): the delta-
        # pressure policy off the flush hot path — the tee ingest no
        # longer compacts inline; the paced thread (lease-gated) does.
        # Owned here so drain() can stop it in dependency order.
        self.compactor = compactor
        self.datastore = datastore
        if compactor is not None:
            compactor.start()
        # durable state (StateStore): restore open batches + tile slices
        # from the last snapshot — the reference instead loses in-memory
        # state on crash (BatchingProcessor.java:20-22, SURVEY.md §5)
        self.state = state
        self.restored = bool(
            state and state.restore(self.batcher, self.anonymiser))

    def offer(self, message: str) -> None:
        """One raw message through the topology."""
        # the per-message span (no-op flag check unless tracing is
        # armed) opens BEFORE the crash failpoint so a SIGKILL-grade
        # death lands inside it — the flight-recorder postmortem then
        # names this exact span as in flight
        with obs_trace.span("worker.offer"):
            # chaos hook: lets a harness kill the worker at an exact
            # stream position ("crash at the Nth offer") — one flag
            # check when off
            faults.failpoint("worker.offer")
            now_ms = int(self.clock() * 1000)
            try:
                uuid, point = self.formatter.format(message)
            except Exception:
                self.parse_failures += 1
                if self.parse_failures % 1000 == 1:
                    logger.warning("Could not parse message: %r",
                                   message[:200])
                return
            if self.uuid_filter is not None and not self.uuid_filter(uuid):
                self.skipped_other_host += 1
                return
            self.batcher.process(uuid, point, now_ms)
            self.processed += 1
            if self.processed % 10000 == 0:
                logger.info("Processed %d messages", self.processed)
            self.maybe_punctuate()
            # streaming backpressure (ISSUE 15): when the submit-latency
            # EWMA or requeue depth crosses its threshold, the offer
            # loop BLOCKS (bounded) before the next message — the
            # slowdown propagates upstream instead of memory absorbing
            # it. Real wall sleep on purpose: injected replay clocks
            # must not defeat flow control.
            delay = self.batcher.offer_delay()
            if delay > 0.0:
                metrics.count("backpressure.delays")
                time.sleep(delay)

    def maybe_punctuate(self, force: bool = False) -> None:
        now = self.clock()
        flushed = False
        if self.batcher.pending and (
                force or now - self._last_report_flush
                >= self.report_flush_interval_s):
            self.batcher.flush_pending()
            self._last_report_flush = now
        if force or (now - self._last_evict) * 1000 >= 2 * self.session_gap_ms:
            self.batcher.punctuate(int(now * 1000))
            self._last_evict = now
            flushed = True
        if force or now - self._last_flush >= self.flush_interval_s:
            self._flush_tiles()
            self._last_flush = now
            flushed = True
        if self.state is not None:
            # tiles just egressed (an external side effect) — snapshot
            # NOW, else a crash would restore and re-emit them. A failed
            # snapshot degrades (wider replay window, counted) instead
            # of killing the stream; the flush-epoch marker keeps the
            # widened window duplicate-free.
            try:
                if flushed:
                    self.state.save(self.batcher, self.anonymiser)
                else:
                    self.state.maybe_save(self.batcher, self.anonymiser)
            except Exception as e:
                metrics.count("state.save.fail")
                logger.error("state snapshot failed (will retry): %s", e)
        if self.drainer is not None:
            self.drainer.maybe_drain()
        if self.heartbeat_s > 0:
            self._maybe_heartbeat()

    def _maybe_heartbeat(self) -> None:
        """Emit the structured heartbeat when its wall interval elapsed:
        one JSON line with throughput, in-flight state, the flush epoch
        and the circuit state — the reference's 10k counter, made
        chartable. Paced by message arrival (the worker is single
        threaded by design): a stalled input emits none, which IS the
        stall signal — no background thread to lock against."""
        now = time.monotonic()
        dt = now - self._hb_last
        if dt < self.heartbeat_s:
            return
        rate = (self.processed - self._hb_processed) / dt if dt > 0 else 0.0
        self._hb_last = now
        self._hb_processed = self.processed
        waste = profiler.padding_waste()
        logger.info("heartbeat %s", json.dumps({
            "processed": self.processed,
            "msgs_per_s": round(rate, 1),
            "batches_in_flight": len(self.batcher.store),
            "pending_reports": len(self.batcher.pending),
            "flush_epoch": self.anonymiser.flush_epoch,
            "circuit": self.circuit_probe() if self.circuit_probe
            else None,
            # which guarded domains are serving degraded right now
            # (open breakers; [] = all closed, None = no probe wired)
            "degraded": self.degraded_probe() if self.degraded_probe
            else None,
            # dead-letter backlog gauges: a drain stall shows up as a
            # growing spool long before the disk alarm does. THIS
            # worker's roots, not the module globals — in a multi-worker
            # process every heartbeat must gauge its own spools (TTL-
            # cached: a full spool must not turn heartbeats into walks)
            "deadletter": {
                "tiles": spool_mod.backlog_cached(self._tile_spool),
                "traces": spool_mod.backlog_cached(self._trace_spool)},
            "parse_failures": self.parse_failures,
            # delta-pressure backlog (cached last compactor sweep):
            # partitions over pressure waiting on background compaction
            "datastore_backlog": self.compactor.pending()
            if self.compactor is not None else None,
            # the device-compute vitals (obs/profiler.py): padding the
            # fixed buckets pay, compile churn, shadow-oracle verdicts
            "padding_waste": round(waste, 4) if waste is not None
            else None,
            "compile_count": profiler.compile_count(),
            "shadow_mismatches": profiler.shadow_mismatches(),
            # load management (ISSUE 15): the process-wide degradation-
            # ladder state and this worker's backpressure sensors — a
            # pressured fleet is visible in the heartbeat stream long
            # before a dashboard is opened
            "pressure": _pressure_level(),
            "backpressure": self.batcher.governor.snapshot(),
            # carried incremental decode state (ISSUE 19): live traces,
            # state bytes vs budget, eviction/fallback counters — the
            # per-worker view of match.incremental.* (None = no probe
            # wired, or the table was never built)
            "incremental": self.incremental_probe()
            if self.incremental_probe else None,
            # which map build this worker matches against (None until
            # the owner wires it): swaps are visible per worker in the
            # heartbeat stream, not just on the serving tier's /health
            "map_version": self.map_version,
        }, separators=(",", ":")))

    def _flush_tiles(self) -> None:
        """Tile egress bracketed by durability barriers.

        Pre-egress snapshot: the reports that fed this flush already
        trimmed their batches; making those trims durable BEFORE the
        tiles leave the process means a crash anywhere in the flush
        cannot restore untrimmed batches that would re-report (and so
        re-emit) segments the sink already has. Post-egress, the
        committed-epoch marker lands AFTER the sink ack and BEFORE the
        next snapshot, so restore can tell "flushed then crashed"
        (skip the epoch) from "crashed mid-flush" (re-emit under the
        same deterministic names — an overwrite, not a duplicate)."""
        # the barrier only matters when something can actually egress —
        # an idle interval must not pay a full fsync'd snapshot
        if self.state is not None and self.anonymiser.slice_of:
            try:
                self.state.save(self.batcher, self.anonymiser)
            except Exception as e:
                metrics.count("state.save.fail")
                logger.error("pre-flush snapshot failed (flushing "
                             "anyway): %s", e)
        epoch = self.anonymiser.flush_epoch
        written = self.anonymiser.punctuate()
        # chaos hook: THE window the flush-epoch machinery exists for —
        # tiles at the sink, nothing durable about it yet
        faults.failpoint("worker.post_egress")
        # only a flush that fully reached the sink commits its epoch: a
        # partial/failed egress leaves the marker behind so a restore
        # retries the epoch (failed tiles are in the dead-letter spool
        # either way), and an empty flush skips the fsync entirely
        if self.state is not None and written > 0:
            try:
                self.state.commit_epoch(epoch)
            except Exception as e:
                # degraded: a restore would re-emit this epoch under the
                # same deterministic names (overwrite, not duplicate)
                metrics.count("state.epoch_commit.fail")
                logger.error("flush-epoch commit failed for %d: %s",
                             epoch, e)

    def drain(self) -> None:
        """End of stream: evict every open batch, then stop in
        dependency order (ISSUE 10) — JOIN the shadow-accuracy pool and
        give the dead-letter replayer a final drain + PAUSE before the
        final flush, so no thread outlives the spool/datastore handles
        that flush is about to release. The drain_now still runs before
        the flush so replayed traces' segments make this last flush
        instead of stranding in the spool."""
        self.batcher.punctuate(int(self.clock() * 1000) + 10 * self.session_gap_ms)
        # shadow-oracle jobs read the profiler ring and count metrics;
        # a straggler completing after the final flush would race the
        # teardown below it. Joined here, it simply cannot.
        profiler.shutdown_shadow_pool()
        if self.drainer is not None:
            self.drainer.drain_now()
            # paused, not just drained: a maybe_punctuate from a late
            # caller must not re-enter the submit path or the sink
            self.drainer.pause()
        self._flush_tiles()
        if self.compactor is not None:
            # signal + JOIN after the final flush (its deltas were the
            # last pressure source): no compaction thread may outlive
            # the store handles this worker is about to release
            self.compactor.stop()
        if self.state is not None:
            self.state.save(self.batcher, self.anonymiser)
        if self.datastore is not None:
            # hand the writer lease back on a CLEAN exit: the
            # successor then acquires a vacant lease instead of
            # "stealing" from a dead pid on every routine restart —
            # steal counters/warnings stay a crash signal
            self.datastore.lease.release()

    def run(self, messages: Iterable[str],
            duration_s: Optional[float] = None) -> None:
        deadline = self.clock() + duration_s if duration_s else None
        try:
            for message in messages:
                self.offer(message)
                if deadline is not None and self.clock() > deadline:
                    break
        except Exception as e:
            # an unhandled exception is about to kill the stream: leave
            # a postmortem naming the span that was in flight
            flightrec.dump("worker.exception", {"error": repr(e)})
            raise
        self.drain()


def resolve_uuid_filter(mode: str, bootstrap: Optional[str]):
    """Decide the multi-host uuid ownership filter.

    The sha1 filter makes N workers reading one SHARED unpartitioned
    stream process each uuid exactly once — Kafka's keyed-partition
    contract without Kafka (parallel.multihost). But when the input IS a
    Kafka consumer group (``bootstrap`` set), the group already
    partitions messages across workers; composing the sha1 filter on top
    silently drops ~(N-1)/N of each worker's share (the round-1..3
    composition bug). So: ``auto`` = filter on for shared inputs, OFF
    under a consumer group; ``on``/``off`` force it (``on`` is for
    unkeyed topics, where group partitioning does not follow uuid —
    with a loud warning).
    """
    from ..parallel import host_uuid_filter
    if mode == "off" or (mode == "auto" and bootstrap):
        return None
    uuid_filter = host_uuid_filter()
    if bootstrap and uuid_filter is not None:
        logger.warning(
            "--uuid-filter=on with a Kafka consumer group: unless the "
            "topic is unkeyed, group partitioning x sha1 filter drops "
            "most messages on every worker")
    return uuid_filter


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-stream",
        description="TPU-native streaming reporter worker")
    parser.add_argument("-f", "--formatter", required=True,
                        help="one-string formatter config (see README)")
    parser.add_argument("-u", "--reporter-url",
                        help="matcher service URL; omit to match in-process")
    parser.add_argument("--graph",
                        help="RoadNetwork .npz for in-process matching")
    parser.add_argument("-m", "--mode", default="auto")
    parser.add_argument("-r", "--reports", default="0,1")
    parser.add_argument("-x", "--transitions", default="0,1")
    parser.add_argument("-p", "--privacy", type=int, required=True)
    parser.add_argument("-q", "--quantisation", type=int, required=True)
    parser.add_argument("-i", "--flush-interval", type=int, required=True)
    parser.add_argument("-s", "--source", required=True)
    parser.add_argument("-o", "--output-location", required=True)
    parser.add_argument("-d", "--duration", type=int)
    parser.add_argument("--input", default="-",
                        help="flat file to replay, '-' for stdin")
    parser.add_argument("-b", "--bootstrap", help="Kafka bootstrap servers")
    parser.add_argument("--uuid-filter", choices=("auto", "on", "off"),
                        default="auto",
                        help="multi-host uuid ownership filter: auto = on "
                        "for shared unpartitioned inputs, OFF when a Kafka "
                        "consumer group already partitions (--bootstrap); "
                        "on/off force it")
    parser.add_argument("-t", "--topics",
                        help="comma-separated topics; first is raw input")
    parser.add_argument("--state-file",
                        help="durable state snapshot path; restored on "
                             "start, saved every --state-interval seconds")
    parser.add_argument("--state-interval", type=float, default=30.0)
    parser.add_argument("--report-flush-interval", type=float, default=1.0,
                        help="wall-clock bound (s) on how long a "
                             "threshold-crossed session waits for a "
                             "batched report flush; a huge value makes "
                             "flush boundaries a pure function of the "
                             "stream (deterministic replays/chaos runs)")
    parser.add_argument("--datastore",
                        help="local histogram-store directory: every "
                             "flushed tile is ALSO aggregated in-process "
                             "(zero serialisation) so /histogram queries "
                             "work without a separate ingest step")
    parser.add_argument("--datastore-max-deltas", type=int, default=None,
                        help="background compaction: compact partitions "
                             "holding more than N uncompacted deltas "
                             "(paced thread off the flush path, "
                             "REPORTER_TPU_COMPACT_INTERVAL_S)")
    parser.add_argument("--datastore-max-delta-bytes", type=int,
                        default=None,
                        help="background compaction: compact partitions "
                             "whose uncompacted deltas exceed B bytes")
    parser.add_argument("--deadletter",
                        help="directory spooling tile bodies whose egress "
                             "failed (default <output>/.deadletter for "
                             "file sinks, <tmpdir>/reporter_tpu_deadletter "
                             "for remote); replay with: datastore ingest "
                             "--delete")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")

    # pin the JAX platform before any decode can block on a chip tunnel;
    # only the in-process matching path touches devices, but deciding up
    # front keeps startup latency out of the first batch flush
    if not args.reporter_url:
        from ..utils.runtime import ensure_backend
        ensure_backend()

    # joins a multi-host JAX job when REPORTER_TPU_COORDINATOR etc. are
    # set; single-host no-op otherwise
    from ..parallel import init_multihost
    init_multihost()
    uuid_filter = resolve_uuid_filter(args.uuid_filter, args.bootstrap)

    circuit_probe = None
    degraded_probe = None
    incremental_probe = None
    incremental_provider = None
    on_evict = None
    if args.reporter_url:
        submit = http_submitter(args.reporter_url)
        submit_many = None  # HTTP path: one POST per trace (split deploy)
    else:
        from ..graph.network import RoadNetwork
        from ..matcher import SegmentMatcher
        from ..service.server import ReporterService
        if not args.graph:
            parser.error("--graph is required for in-process matching")
        service = ReporterService(
            SegmentMatcher(net=RoadNetwork.load(args.graph)))
        submit = inproc_submitter(service)
        # batched submit for eviction flushes: one dispatcher round trip
        # -> one padded device batch. report_incremental routes report-
        # ready sessions through the carried-state path (O(K) per
        # appended point, ISSUE 19) and falls back to the windowed
        # report_many per trace — kill switch REPORTER_TPU_INCREMENTAL
        submit_many = service.report_incremental
        circuit_probe = lambda: service.matcher.circuit.state  # noqa: E731
        degraded_probe = service.matcher.open_domains

        def incremental_probe(_m=service.matcher):
            t = _m._incremental_table
            return t.gauge() if t is not None else None

        # session-gap eviction drops the session's carried decode state
        # with it — AFTER its final relaxed-threshold report flushed
        def on_evict(uuid, _m=service.matcher):
            t = _m._incremental_table
            if t is not None:
                t.evict(uuid, "session gap")

        # snapshot v3 provider: restore must BUILD the table (frames in
        # the snapshot need somewhere to live); save uses the property
        # too — constructing the empty table is dict bookkeeping only
        incremental_provider = lambda: service.matcher.incremental_table  # noqa: E731

    state = None
    if args.state_file:
        from .state import StateStore
        state = StateStore(args.state_file, interval_s=args.state_interval,
                           incremental=incremental_provider)

    tee = None
    datastore = None
    compactor = None
    if args.datastore:
        from ..datastore import BackgroundCompactor, LocalDatastore
        datastore = LocalDatastore(args.datastore)
        # freshness tier (datastore/freshness.py): the tee's ingest
        # records every flushed delta into the recent-delta overlay +
        # change feed, so /histogram?window= and /feed subscribers see
        # a probe within one tee cycle (REPORTER_TPU_FRESHNESS=0 opts
        # out and this is a no-op)
        datastore.enable_freshness()
        max_deltas = args.datastore_max_deltas
        max_bytes = args.datastore_max_delta_bytes
        inline_deltas = inline_bytes = None
        if max_deltas is not None or max_bytes is not None:
            # the pressure policy moved OFF the flush hot path: the tee
            # ingest below never compacts inline any more — the paced
            # background thread (lease-gated, so exactly one compactor
            # per store root across processes) sweeps instead. EXCEPT
            # when the operator disabled the thread
            # (REPORTER_TPU_COMPACT_INTERVAL_S=0): the explicit
            # --datastore-max-deltas flags must still mean something,
            # so the tee falls back to the old inline pressure check
            from ..datastore.compactor import compact_interval_s
            if compact_interval_s() > 0:
                compactor = BackgroundCompactor(datastore,
                                                max_deltas=max_deltas,
                                                max_delta_bytes=max_bytes)
            else:
                inline_deltas, inline_bytes = max_deltas, max_bytes

        def tee(_tile, segments, ingest_key=None, _ds=datastore,
                _n=inline_deltas, _b=inline_bytes):
            # ingest_key is the flush identity the anonymiser stamps —
            # the exactly-once ledger key that makes crash-replayed
            # flushes idempotent. A LeaseHeldElsewhere (another process
            # owns the store) propagates like any tee failure: the
            # anonymiser spools the tile body for later replay
            return _ds.ingest_segments(segments, max_deltas=_n,
                                       max_delta_bytes=_b,
                                       ingest_key=ingest_key)

    worker = StreamWorker(
        Formatter.from_config(args.formatter), submit,
        Anonymiser(TileSink(args.output_location,
                            deadletter=args.deadletter),
                   args.privacy,
                   args.quantisation, mode=args.mode, source=args.source,
                   tee=tee),
        mode=args.mode, reports=args.reports, transitions=args.transitions,
        flush_interval_s=args.flush_interval, state=state,
        uuid_filter=uuid_filter, submit_many=submit_many,
        report_flush_interval_s=args.report_flush_interval,
        circuit_probe=circuit_probe, degraded_probe=degraded_probe,
        incremental_probe=incremental_probe, on_evict=on_evict,
        datastore=datastore, compactor=compactor)
    if not args.reporter_url:
        # in-process matching: the heartbeat carries the graph's
        # content-derived map version (an HTTP split can't know the
        # remote matcher's build). NOTE: the tee's ledger keys stay
        # UNversioned here — only a map-version OWNER (the city
        # registry's swap machinery, harnesses) stamps the store, so a
        # single-map worker's crash-replay dedupe is byte-compatible
        # with pre-versioning spools
        try:
            from ..graph.version import map_version as _map_version
            worker.map_version = _map_version(service.matcher.net)
        except Exception:
            pass
    if not args.reporter_url:
        # poisoned-trace quarantine lands in THIS worker's trace spool
        # (explicit beats the last-writer-wins module global — see
        # StreamWorker.__init__)
        service.matcher.quarantine_spool = worker._trace_spool

    # the flat-file input is opened under an ExitStack so the handle
    # closes on every exit path (drain, exception, --duration cut-off)
    with contextlib.ExitStack() as stack:
        if args.bootstrap:
            from .broker import KafkaBroker
            broker = KafkaBroker(args.bootstrap)
            raw_topic = (args.topics or "raw").split(",")[0]
            messages = (value.decode()
                        for _key, value in broker.consume(raw_topic))
        elif args.input == "-":
            messages = (line for line in sys.stdin)
        else:
            messages = stack.enter_context(open(args.input))
        worker.run(messages, duration_s=args.duration)
    logger.info("Done: %d processed, %d parse failures",
                worker.processed, worker.parse_failures)
    return 0


if __name__ == "__main__":
    sys.exit(main())
