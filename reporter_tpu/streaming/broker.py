"""Stream transport adapters.

The reference's backbone is Kafka topics between processors
(reference: Reporter.java:156-181). Here the topology runs in one process
with an in-memory broker by default (the TPU wants all stages co-located
with the device), while a Kafka adapter (gated on the client library being
installed) preserves the reference's deployment shape: ``raw`` in,
formatted/segment topics through, for multi-worker scale-out partitioned
by uuid so per-uuid point order is preserved (reference: tests/circle.sh:58,
README "Kafka stream configuration").
"""
from __future__ import annotations

import queue
from typing import Iterator, Optional


class InMemoryBroker:
    """Topic -> queue map; single-process stand-in for Kafka."""

    def __init__(self):
        self.topics: dict[str, queue.Queue] = {}

    def topic(self, name: str) -> queue.Queue:
        return self.topics.setdefault(name, queue.Queue())

    def produce(self, topic: str, key, value) -> None:
        self.topic(topic).put((key, value))

    def consume(self, topic: str, timeout: Optional[float] = None
                ) -> Iterator[tuple]:
        q = self.topic(topic)
        while True:
            try:
                yield q.get(timeout=timeout)
            except queue.Empty:
                return


def kafka_available() -> bool:
    try:
        import kafka  # noqa: F401
        return True
    except ImportError:
        return False


class KafkaBroker:
    """Thin wrapper over kafka-python, keyed by uuid so partition order
    matches the reference's requirement. Only constructible when the
    client library is installed."""

    def __init__(self, bootstrap: str):
        if not kafka_available():
            raise RuntimeError(
                "kafka-python is not installed in this environment; "
                "use InMemoryBroker or install the client")
        from kafka import KafkaConsumer, KafkaProducer  # type: ignore
        self._producer_cls = KafkaProducer
        self._consumer_cls = KafkaConsumer
        self.bootstrap = bootstrap
        self._producer = None

    def producer(self):
        if self._producer is None:
            self._producer = self._producer_cls(
                bootstrap_servers=self.bootstrap,
                key_serializer=lambda k: k.encode() if k else None,
                value_serializer=lambda v: v)
        return self._producer

    def produce(self, topic: str, key: str, value: bytes) -> None:
        self.producer().send(topic, key=key, value=value)

    def consume(self, topic: str, group: str = "reporter"):
        consumer = self._consumer_cls(
            topic, bootstrap_servers=self.bootstrap, group_id=group)
        for msg in consumer:
            yield msg.key.decode() if msg.key else None, msg.value
