"""Automated dead-letter replay: the spools drain themselves.

PR 5 defined the loss paths — failed tile egress spools CSV bodies to
``.deadletter`` in the flush layout, an exhausted submit budget spools
trace request JSON to ``.traces`` — and left replay manual (`datastore
ingest --delete`, or POSTing bodies by hand). This module closes the
loop: a :class:`DeadLetterDrainer` owned by the streaming worker
re-submits spooled traces through the SAME submit path the live stream
uses (responses forward into the anonymiser, so no observation is lost)
and re-egresses spooled tiles through the SAME sink (deterministic
epoch-named files, so a replay can only overwrite, never duplicate).

Discipline:

- **Paced, on the worker thread.** ``maybe_drain`` rides punctuation
  (``REPORTER_TPU_REPLAY_INTERVAL_S``; 0 — the default — disables), the
  same single-threaded pacing the heartbeat uses: the anonymiser and
  batcher have no locks, so the drainer must never touch them from a
  second thread.
- **Capped exponential backoff per entry.** A failed replay backs its
  entry off ``base * 2^attempts`` seconds (capped), so a still-down
  sink is probed, not hammered.
- **Poison quarantine.** An entry still failing after
  ``REPORTER_TPU_REPLAY_ATTEMPTS`` attempts moves to a ``.quarantine``
  subdir (dot-prefixed — every scanner skips it) for manual autopsy:
  one poison body must not wedge the drain behind it forever.
- **Trace replay is at-least-once.** Tile replay is exactly-once (the
  deterministic epoch name dedupes the sink, the manifest ledger the
  store); a replayed TRACE's segments re-enter the live pipeline as
  fresh observations, so a crash in the window between forwarding them
  and unlinking the spool entry replays the trace again on restart
  under a new flush epoch — a duplicate, not a loss. Unlinking first
  would flip that to silent loss on the mirror-image crash; duplicates
  were chosen because they are at least visible. The window is one
  entry wide and only open while a drain pass is mid-flight.

``tools/replay_cli.py`` drives the same class standalone (one-shot
``drain_now``) against a spool directory + service URL / sink for
split deployments.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import faults, metrics, spool

logger = logging.getLogger("reporter_tpu.streaming")

QUARANTINE = ".quarantine"


def replay_knobs() -> Tuple[float, int]:
    """(interval_s, max_attempts) from the environment."""
    from ..utils.runtime import _env_float, _env_int
    return (_env_float("REPORTER_TPU_REPLAY_INTERVAL_S", 0.0),
            _env_int("REPORTER_TPU_REPLAY_ATTEMPTS", 5))


class DeadLetterDrainer:
    """Drains a tile spool (flush-layout CSV bodies) and its nested
    ``.traces`` spool (/report-ready request JSON) back into the
    pipeline.

    ``submit`` is the worker's match round trip (request dict ->
    response dict or None); ``forward`` receives the replayed
    responses' (key, Segment) pairs (the anonymiser hook) — without it
    a successful re-submit still clears the spool entry but the
    segments go nowhere, which is only correct for the standalone CLI
    posting to a REMOTE service that owns its own pipeline. ``sink`` is
    the TileSink failed tiles re-egress through.
    """

    def __init__(self, tile_root: Optional[str],
                 trace_root: Optional[str] = None,
                 submit: Optional[Callable[[dict], Optional[dict]]] = None,
                 forward: Optional[Callable] = None,
                 sink=None,
                 datastore=None,
                 interval_s: float = 30.0,
                 max_attempts: int = 5,
                 base_backoff_s: float = 1.0,
                 max_backoff_s: float = 60.0,
                 backoff_jitter: float = 0.25,
                 jitter_seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.tile_root = tile_root
        if trace_root is None and tile_root:
            trace_root = os.path.join(tile_root, ".traces")
        self.trace_root = trace_root
        self.submit = submit
        self.forward = forward
        self.sink = sink
        # with a co-located datastore, spooled tiles replay into it too
        # (relpath ledger key — a tile the tee already ingested dedupes;
        # a tile whose tee FAILED finally lands): the spool covers both
        # consumers, so neither can lose what the other received
        self.datastore = datastore
        self.interval_s = interval_s
        self.max_attempts = max(1, int(max_attempts))
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        # seeded jitter on the capped exponential backoff: a fleet of
        # workers recovering from ONE outage all hit the same capped
        # schedule, so without jitter every re-submit lands in
        # thundering-herd lockstep against the sink that just came
        # back. Each entry's delay stretches by a uniform draw in
        # [0, backoff_jitter]; the RNG seeds from the pid by default
        # (distinct per fleet member) and from ``jitter_seed`` in tests
        # — the whole schedule is then deterministic by seed.
        self.backoff_jitter = max(0.0, float(backoff_jitter))
        self._jitter_rng = random.Random(
            os.getpid() if jitter_seed is None else jitter_seed)
        self.clock = clock
        self._next_pass = clock()
        # budget key -> consecutive failed attempts; entries leave the
        # table on success or quarantine, and keys with no live file
        # are pruned each pass. Keyed by _budget_key, NOT path: a
        # poison body the matcher re-quarantines under a fresh counter
        # name must share its predecessor's budget, or it cycles
        # through new names forever without ever reaching .quarantine
        self._attempts: Dict[str, int] = {}
        self._due: Dict[str, float] = {}
        # worker-shutdown latch: a paused drainer's maybe_drain is a
        # no-op, so nothing can re-enter the submit path or the sink
        # after the worker's final flush released them (ISSUE 10
        # shutdown-ordering contract)
        self._paused = False

    def pause(self) -> None:
        """Stop paced drains (worker shutdown): after the final
        drain_now, no maybe_drain may touch the submit path or sink
        again — their handles are about to be released."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    # -- spool walks -------------------------------------------------------
    # both walks share spool.walk_files — the one definition of "what
    # counts as a spool entry" (dot/tmp files and nested spools like
    # .quarantine excluded), so the drainer can never replay something
    # the cap/backlog layer doesn't count, or vice versa
    def _trace_entries(self, cap: Optional[int] = None) -> List[str]:
        return self._entries(self.trace_root, cap)

    def _tile_entries(self, cap: Optional[int] = None) -> List[str]:
        return self._entries(self.tile_root, cap)

    @staticmethod
    def _entries(root: Optional[str], cap: Optional[int]) -> List[str]:
        """``cap`` bounds the directory walk itself (paced passes run
        on the stream thread — a 200k-entry outage backlog must not
        cost a full os.walk+stat sweep per interval); the un-walked
        tail is simply later passes' work."""
        if not root or not os.path.isdir(root):
            return []
        paths = (p for p, _sz, _mt in spool.walk_files(root, True))
        if cap is not None:
            paths = itertools.islice(paths, cap)
        return sorted(paths)

    def backlog(self) -> Dict[str, int]:
        """{"tiles": n, "traces": n} — what is left to drain."""
        return {"tiles": len(self._tile_entries()),
                "traces": len(self._trace_entries())}

    # -- replay ------------------------------------------------------------
    def _budget_key(self, root: Optional[str], path: str) -> str:
        """Stable attempt-budget identity for a spool entry. Trace
        bodies are named ``{prefix}.{uuid}.json`` by the batcher AND by
        the matcher's poison quarantine (uuids are caller-supplied and
        may themselves contain dots, so take everything between the
        FIRST dot and the ``.json`` suffix — never a rightmost-token
        parse that would collapse distinct dotted uuids onto one
        budget), so a body that gets re-spooled under a fresh name
        during its own replay keeps burning the same budget. Tile names
        are already deterministic — the path is the identity."""
        if root == self.trace_root:
            name = os.path.basename(path)
            if name.endswith(".json") and "." in name[:-5]:
                return "uuid:" + name[:-5].split(".", 1)[1]
        return path

    def _replay_trace(self, path: str) -> bool:
        if self.submit is None:
            return False
        with open(path, encoding="utf-8") as f:
            body = json.load(f)
        # the same failure domain the live submit path runs under: a
        # chaos scenario arming matcher.submit holds replays down too
        faults.failpoint("matcher.submit")
        # a deterministically-poisoned body makes the IN-PROCESS matcher
        # re-quarantine it (a fresh spool entry) while returning a
        # well-formed empty match — without this delta check that reads
        # as success, the old entry unlinks, and the poison cycles
        # spool->replay->spool forever. Counting it as a failure sends
        # it down the normal backoff -> .quarantine road. (Concurrent
        # live-traffic quarantines can trip this too; that mis-scores
        # one attempt, not the entry — it just backs off and retries.)
        q0 = metrics.default.counter("matcher.assemble.quarantined")
        response = self.submit(body)
        if response is None:
            return False
        if metrics.default.counter("matcher.assemble.quarantined") > q0:
            return False
        if self.forward is not None:
            from .batcher import segments_from_response
            for key, seg in segments_from_response(response):
                self.forward(key, seg)
        return True

    def _replay_tile(self, path: str) -> bool:
        if self.sink is None and self.datastore is None:
            return False
        rel = os.path.relpath(path, self.tile_root)
        tile_name, file_name = os.path.split(rel)
        tile_name = tile_name.replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            payload = f.read()
        if self.datastore is not None:
            # idempotent (ledger key == the relpath the tee stamped);
            # raises on a down store — or a writer lease another
            # process holds (LeaseHeldElsewhere) — -> counted failure,
            # backed off, retried after the holder's TTL
            from ..datastore import parse_tile_csv
            self.datastore.ingest(parse_tile_csv(payload),
                                  ingest_key=f"{tile_name}/{file_name}")
        if self.sink is None:
            return True
        # a failed store re-spools the body under the SAME deterministic
        # name (an overwrite) and returns False — the entry just stays
        return self.sink.store(tile_name, file_name, payload)

    def _quarantine(self, root: str, path: str) -> None:
        rel = os.path.relpath(path, root)
        dest = os.path.join(root, QUARANTINE, rel)
        try:
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            os.replace(path, dest)
            metrics.count("replay.quarantined")
            logger.error("dead-letter entry still failing after %d "
                         "attempts; quarantined to %s",
                         self.max_attempts, dest)
        except OSError as e:
            logger.error("could not quarantine %s: %s", path, e)

    def _drain_one(self, root: str, path: str, replay, ok_metric: str,
                   fail_metric: str, now: float,
                   ignore_backoff: bool) -> bool:
        key = self._budget_key(root, path)
        if not ignore_backoff and now < self._due.get(key, 0.0):
            return False
        try:
            ok = replay(path)
            err = None
        except Exception as e:
            ok = False
            err = e
        if ok:
            try:
                os.unlink(path)
            except OSError:
                pass
            self._attempts.pop(key, None)
            self._due.pop(key, None)
            metrics.count(ok_metric)
            return True
        metrics.count(fail_metric)
        attempts = self._attempts.get(key, 0) + 1
        if err is not None:
            logger.warning("dead-letter replay failed for %s "
                           "(attempt %d/%d): %s", path, attempts,
                           self.max_attempts, err)
        if attempts >= self.max_attempts:
            self._attempts.pop(key, None)
            self._due.pop(key, None)
            self._quarantine(root, path)
            return False
        self._attempts[key] = attempts
        backoff = min(self.base_backoff_s * (2.0 ** (attempts - 1)),
                      self.max_backoff_s)
        # jitter AFTER the cap: capped entries are exactly the ones a
        # whole recovering fleet would otherwise retry in lockstep
        backoff *= 1.0 + self.backoff_jitter * self._jitter_rng.random()
        self._due[key] = now + backoff
        return False

    #: replay attempts one paced pass may spend: maybe_drain runs on the
    #: ONE stream-processing thread, and during an outage every backlog
    #: entry comes due together (backoff caps at max_backoff_s) — an
    #: unbounded pass would stall the live stream for the whole
    #: backlog's worth of submit timeouts. The remainder waits for the
    #: next punctuation; drain_now (end of stream, nothing live to
    #: starve) is unbounded per pass.
    MAX_PER_PASS = 32
    #: spool entries one paced pass will even LIST (the walk itself is
    #: O(entries) stats on the stream thread)
    WALK_CAP = 2048

    def _pass(self, now: float, ignore_backoff: bool,
              only: Optional[set] = None,
              limit: Optional[int] = None,
              walk_cap: Optional[int] = None) -> int:
        drained = attempted = 0
        traces = self._trace_entries(walk_cap)
        tiles = self._tile_entries(walk_cap)
        # a drainer built without a submitter (tile-only CLI) must not
        # burn the trace spool's attempt budget, and vice versa
        work = []
        if self.submit is not None:
            work += [(self.trace_root, p, self._replay_trace,
                      "replay.traces.ok", "replay.traces.fail")
                     for p in traces]
        if self.sink is not None or self.datastore is not None:
            work += [(self.tile_root, p, self._replay_tile,
                      "replay.tiles.ok", "replay.tiles.fail")
                     for p in tiles]
        for root, path, replay, ok_metric, fail_metric in work:
            if only is not None and path not in only:
                continue
            if limit is not None and attempted >= limit:
                break
            if not ignore_backoff \
                    and now < self._due.get(
                        self._budget_key(root, path), 0.0):
                continue  # backed off, not an attempt
            attempted += 1
            if self._drain_one(root, path, replay, ok_metric,
                               fail_metric, now, ignore_backoff):
                drained += 1
        # budget keys with no live file left (cap shed, operator unlink)
        # must not pin attempt/backoff state forever — but only prune
        # off a COMPLETE walk: a capped listing proves nothing absent
        if walk_cap is None or (len(traces) < walk_cap
                                and len(tiles) < walk_cap):
            live = {self._budget_key(self.trace_root, p) for p in traces} \
                | {self._budget_key(self.tile_root, p) for p in tiles}
            for table in (self._attempts, self._due):
                for key in [k for k in table if k not in live]:
                    table.pop(key, None)
        return drained

    def maybe_drain(self) -> int:
        """One paced drain pass (the worker punctuation hook); returns
        entries drained. Interval-gated so an idle spool costs two
        directory existence checks per punctuation, and bounded to
        MAX_PER_PASS replay attempts so a deep backlog cannot stall the
        stream thread."""
        if self._paused:
            return 0
        now = self.clock()
        if now < self._next_pass:
            return 0
        self._next_pass = now + self.interval_s
        return self._pass(now, ignore_backoff=False,
                          limit=self.MAX_PER_PASS,
                          walk_cap=self.WALK_CAP)

    def drain_now(self) -> int:
        """Drain until empty or until a full pass makes no progress
        (end-of-stream / CLI mode; per-entry backoff is ignored but the
        attempt budget and quarantine still apply, so a dead sink
        terminates instead of spinning). Bounded to the entries present
        when the drain started: anything spooled DURING it (a live
        stream's fresh dead-letters, a poison body re-quarantining
        itself mid-replay) belongs to the next drain — without the
        snapshot, a self-re-spooling entry makes this loop never
        terminate. Returns total entries drained."""
        total = 0
        initial = set(self._trace_entries()) | set(self._tile_entries())
        while True:
            got = self._pass(self.clock(), ignore_backoff=True,
                             only=initial)
            total += got
            if not got:
                return total
            left = set(self._trace_entries()) | set(self._tile_entries())
            if not (left & initial):
                return total


__all__ = ["DeadLetterDrainer", "replay_knobs", "QUARANTINE"]
