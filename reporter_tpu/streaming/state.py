"""Durable streaming state: snapshot/restore for batcher + anonymiser.

The reference's streaming worker keeps all state in **in-memory** Kafka
Streams stores — explicitly not RocksDB — so a crash loses every open
per-uuid batch and every accumulated tile slice
(reference: BatchingProcessor.java:20-22, AnonymisingProcessor.java:47-59).
SURVEY.md §5 flags that as the durability decision to improve on.

This module is the improvement: a periodic, atomic, binary snapshot of
the worker's two state stores, restored on startup. The wire layouts are
the framework's own fixed-width serdes (Point 20 B, Segment 40 B,
TimeQuantisedTile 16 B — core/types.py), so the snapshot stays compact
and the serde code paths get exercised in production. Writes go to a tmp
file (fsync'd, then the directory fsync'd after ``os.replace`` — a bare
rename can survive a power loss as an empty file) so a crash mid-write
leaves the previous snapshot intact; restore of a truncated/corrupt file
is treated as "no snapshot" (the reference's crash semantics) rather
than an error.

Exactly-once-ish egress (three-step flush protocol, worker._flush_tiles):
(1) a PRE-egress snapshot makes the report trims that fed the flush
durable — no crash can restore untrimmed batches that would re-report
already-egressed segments; (2) the tiles egress under deterministic
epoch file names; (3) :meth:`StateStore.commit_epoch` durably marks the
epoch fully egressed in a sidecar file (``<path>.epoch``) before the
post-flush snapshot. A crash after the marker restores the pre-flush
snapshot, detects ``committed >= snapshot.flush_epoch`` and skips the
epoch — clearing the restored slices instead of double-emitting them; a
crash *before* the marker re-emits the epoch under the same names,
which the file sink overwrites byte-identically and remote sinks dedupe
on — every window is covered.

Layout (little-endian, "RTS1" magic, version 3; v1 snapshots predate the
flush epoch and are discarded as corrupt — the reference's crash
semantics, one replay window wide. v2 snapshots are READ compatibly:
they simply predate the incremental section, which is a pure work-saving
cache — restoring none of it just means those traces re-decode their
window on the next report):

  header:  4s magic | u32 version | u64 snapshot_unix_ms
  epoch:   u64 flush_epoch
  batches: u32 count, then per uuid:
           u16 uuid_len | uuid utf-8 | f32 max_separation |
           u64 last_update_ms | u32 retries | u32 n_points |
           n_points * Point
  pending: u32 count, then per uuid: u16 uuid_len | uuid utf-8
           (sessions awaiting a batched report flush — restoring them
           keeps flush boundaries deterministic across a crash)
  slices:  u32 count, then per slice:
           u16 name_len | name utf-8 | u32 n_segments | n * Segment
  slice_of: u32 count, then per tile: Tile | u32 slice_no
  incremental (v3+): u32 count, then per uuid:
           u16 uuid_len | uuid utf-8 | u32 blob_len | blob
           (opaque CarriedState frames, matcher/incremental.py serde —
           crash-restore resumes mid-stream incremental decode instead
           of paying a full-window replay per live session)
"""
from __future__ import annotations

import logging
import os
import struct
import time
from typing import Optional

from ..core.types import Point, Segment, TimeQuantisedTile
from ..utils import faults, fsio, metrics
from .batcher import Batch, PointBatcher
from .anonymiser import Anonymiser

logger = logging.getLogger("reporter_tpu.streaming")

_MAGIC = b"RTS1"
_VERSION = 3
_HEADER = struct.Struct("<4sIQ")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_BATCH_META = struct.Struct("<fQII")


def _pack_str(out: bytearray, s: str) -> None:
    raw = s.encode("utf-8")
    out += _U16.pack(len(raw))
    out += raw


class _Reader:
    def __init__(self, raw: bytes):
        self.raw = raw
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.raw):
            raise ValueError("truncated snapshot")
        chunk = self.raw[self.pos:self.pos + n]
        self.pos += n
        return chunk

    def u16(self) -> int:
        return _U16.unpack(self.take(2))[0]

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self.take(8))[0]

    def string(self) -> str:
        return self.take(self.u16()).decode("utf-8")


def snapshot_bytes(batcher: PointBatcher, anonymiser: Anonymiser,
                   incremental=None) -> bytes:
    """``incremental`` is [(uuid, blob)] carried-state frames
    (IncrementalTable.to_blobs()), or None for an empty section."""
    out = bytearray()
    out += _HEADER.pack(_MAGIC, _VERSION, int(time.time() * 1000))
    out += _U64.pack(anonymiser.flush_epoch)

    out += _U32.pack(len(batcher.store))
    for uuid, batch in batcher.store.items():
        _pack_str(out, uuid)
        out += _BATCH_META.pack(batch.max_separation, batch.last_update,
                                batch.retries, len(batch.points))
        for p in batch.points:
            out += p.to_bytes()

    out += _U32.pack(len(batcher.pending))
    for uuid in batcher.pending:
        _pack_str(out, uuid)

    out += _U32.pack(len(anonymiser.slices))
    for name, segments in anonymiser.slices.items():
        _pack_str(out, name)
        out += _U32.pack(len(segments))
        for s in segments:
            out += s.to_bytes()

    out += _U32.pack(len(anonymiser.slice_of))
    for tile, slice_no in anonymiser.slice_of.items():
        out += tile.to_bytes()
        out += _U32.pack(slice_no)

    frames = incremental or []
    out += _U32.pack(len(frames))
    for uuid, blob in frames:
        _pack_str(out, uuid)
        out += _U32.pack(len(blob))
        out += blob
    return bytes(out)


def restore_bytes(raw: bytes, batcher: PointBatcher,
                  anonymiser: Anonymiser) -> list:
    """Populate the two stores from a snapshot; returns the carried
    incremental-state frames as [(uuid, blob)] (empty for a v2
    snapshot). Raises ValueError on a corrupt/truncated snapshot — in
    that case the stores are left UNTOUCHED (the whole snapshot is
    parsed before anything is applied), so callers can safely treat the
    failure as "no snapshot"."""
    r = _Reader(raw)
    magic, version, _ts = _HEADER.unpack(r.take(_HEADER.size))
    if magic != _MAGIC or version not in (2, _VERSION):
        raise ValueError(f"bad snapshot header {magic!r} v{version}")
    flush_epoch = r.u64()

    store = {}
    for _ in range(r.u32()):
        uuid = r.string()
        max_sep, last_update, retries, n_points = _BATCH_META.unpack(
            r.take(_BATCH_META.size))
        batch = Batch()
        batch.max_separation = max_sep
        batch.last_update = last_update
        batch.retries = retries
        for _ in range(n_points):
            batch.points.append(Point.from_bytes(r.take(Point.SIZE)))
        store[uuid] = batch

    pending = {}
    for _ in range(r.u32()):
        pending[r.string()] = None

    slices = {}
    for _ in range(r.u32()):
        name = r.string()
        slices[name] = [Segment.from_bytes(r.take(Segment.SIZE))
                        for _ in range(r.u32())]

    slice_of = {}
    for _ in range(r.u32()):
        tile = TimeQuantisedTile.from_bytes(r.take(TimeQuantisedTile.SIZE))
        slice_of[tile] = r.u32()

    frames = []
    if version >= 3:
        for _ in range(r.u32()):
            uuid = r.string()
            frames.append((uuid, r.take(r.u32())))

    # parse succeeded in full — apply atomically
    batcher.store.update(store)
    batcher.pending.update(pending)
    anonymiser.slices.update(slices)
    anonymiser.slice_of.update(slice_of)
    anonymiser.flush_epoch = flush_epoch
    return frames


class StateStore:
    """Owns the snapshot file; periodic save + startup restore.

    ``interval_s`` bounds the replay window after a crash: at most that
    many seconds of stream go unsnapshotted (the reference loses
    *everything* open on crash instead).
    """

    def __init__(self, path: str, interval_s: float = 30.0,
                 clock=time.time, incremental=None):
        self.path = path
        self.interval_s = interval_s
        self.clock = clock
        self._last_save = clock()
        # zero-arg callable -> matcher.incremental.IncrementalTable (or
        # None): every save tees the carried decode state into the
        # snapshot and restore hands the frames back, so a crash-restored
        # worker resumes mid-stream incremental decode (snapshot v3)
        self.incremental = incremental
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    # -- committed-epoch marker --------------------------------------------
    @property
    def epoch_path(self) -> str:
        return self.path + ".epoch"

    def commit_epoch(self, epoch: int) -> None:
        """Durably record that ``epoch``'s tiles fully reached the sink.
        Called between egress and the post-flush snapshot — it is what
        lets restore tell "flushed then crashed" from "crashed mid-way"."""
        fsio.atomic_write_text(self.epoch_path, str(int(epoch)))

    def committed_epoch(self) -> int:
        """The last epoch known to have fully egressed; -1 when none."""
        try:
            with open(self.epoch_path, encoding="utf-8") as f:
                return int(f.read().strip())
        except (FileNotFoundError, ValueError):
            return -1

    # -- snapshot ----------------------------------------------------------
    def restore(self, batcher: PointBatcher,
                anonymiser: Anonymiser) -> bool:
        """Load state if a snapshot exists; False when starting fresh.

        When the committed-epoch marker says the snapshot's next flush
        epoch already reached the sink (the crash landed between egress
        and snapshot), the restored tile slices are SKIPPED instead of
        queued for a duplicate emission."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self._seed_epoch(anonymiser)
            return False
        try:
            frames = restore_bytes(raw, batcher, anonymiser)
        except ValueError as e:
            logger.error("Discarding corrupt state snapshot %s: %s",
                         self.path, e)
            self._seed_epoch(anonymiser)
            return False
        if frames:
            table = self.incremental() if self.incremental else None
            if table is not None:
                n = table.restore_blobs(frames)
                logger.info("Restored %d/%d carried incremental decode "
                            "states", n, len(frames))
        committed = self.committed_epoch()
        if committed >= anonymiser.flush_epoch:
            dropped = len(anonymiser.slices)
            anonymiser.slices.clear()
            anonymiser.slice_of.clear()
            anonymiser.flush_epoch = committed + 1
            metrics.count("state.epoch_skipped")
            logger.warning(
                "Snapshot pre-dates committed flush epoch %d: skipping "
                "%d already-egressed tile slices (crash landed between "
                "egress and snapshot)", committed, dropped)
        logger.info("Restored state: %d open batches, %d tile slices, "
                    "flush epoch %d", len(batcher.store),
                    len(anonymiser.slices), anonymiser.flush_epoch)
        return True

    def _seed_epoch(self, anonymiser: Anonymiser) -> None:
        """Fresh-start epoch seeding: even with no usable snapshot, a
        surviving ``.epoch`` marker means epoch-named tiles up to that
        number are already committed at the sink — restarting the
        counter at 0 would deterministically OVERWRITE them with
        different data (the hazard the removed uuid4 names could never
        hit). Resume numbering past the marker instead."""
        committed = self.committed_epoch()
        if committed >= anonymiser.flush_epoch:
            anonymiser.flush_epoch = committed + 1
            logger.warning(
                "No usable snapshot but flush epochs up to %d are "
                "committed; resuming tile numbering at epoch %d",
                committed, committed + 1)

    def save(self, batcher: PointBatcher, anonymiser: Anonymiser) -> None:
        faults.failpoint("state.save")
        table = self.incremental() if self.incremental else None
        frames = table.to_blobs() if table is not None else None
        # tmp + fsync + replace + dir fsync via fsio: os.replace
        # promises atomicity, not durability — after a power loss an
        # un-fsynced rename can legally surface as an EMPTY new name
        fsio.atomic_write_bytes(self.path,
                                snapshot_bytes(batcher, anonymiser,
                                               incremental=frames))
        faults.failpoint("state.save", after=True)
        self._last_save = self.clock()

    def maybe_save(self, batcher: PointBatcher,
                   anonymiser: Anonymiser) -> bool:
        if self.clock() - self._last_save < self.interval_s:
            return False
        self.save(batcher, anonymiser)
        return True
