from .formatter import Formatter
from .batcher import Batch, PointBatcher
from .anonymiser import Anonymiser
from .broker import InMemoryBroker
from .state import StateStore

__all__ = ["Formatter", "Batch", "PointBatcher", "Anonymiser",
           "InMemoryBroker", "StateStore"]
