"""Per-uuid point batching and report triggering.

The streaming analog of the reference's Batch + BatchingProcessor
(reference: Batch.java, BatchingProcessor.java). Semantics preserved:

- a per-uuid window accumulates points, tracking the max separation from
  the *first* point by equirectangular distance (Batch.java:34-47)
- a report fires once the window spans >= 500 m AND >= 10 points AND
  >= 60 s (BatchingProcessor.java:26-28); on response, the consumed prefix
  is trimmed at ``shape_used`` so match context overlaps windows
  (Batch.java:73-80)
- batches idle past the 60 s session gap are evicted with relaxed
  thresholds (0 m, 2 points, 0 s) (BatchingProcessor.java:87-106)
- valid (id, next_id) report pairs are forwarded keyed "id next_id"
  (BatchingProcessor.java:108-141)
- an unparseable matcher response drops the whole batch (Batch.java:83-87)

One reference behavior is deliberately NOT preserved: a failed submit no
longer silently drops the batch. Transient failures requeue the batch
under a small retry budget (``REPORTER_TPU_SUBMIT_RETRIES``, counted as
``batch.requeued``); an exhausted budget dead-letters the trace JSON to
a spool directory for replay and counts ``batch.dropped`` — the matcher
outage failure domain has a defined degraded mode instead of data loss.

What changed for the TPU: the matcher call is pluggable — an in-process
``ReporterService.handle`` (which micro-batches across uuids on the device)
instead of one HTTP POST per trace, though an HTTP submitter is provided
for split deployments.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import time

from ..core.geo import equirectangular_m
from ..core.osmlr import INVALID_SEGMENT_ID
from ..core.tracebatch import TraceBatch, TraceView
from ..core.types import Point, Segment
from ..obs import flightrec
from ..obs import trace as obs_trace
from ..utils import faults, metrics, spool
from .backpressure import BackpressureGovernor

logger = logging.getLogger("reporter_tpu.streaming")

REPORT_TIME = 60       # seconds       (BatchingProcessor.java:26)
REPORT_COUNT = 10      # points        (:27)
REPORT_DIST = 500      # meters        (:28)
SESSION_GAP_MS = 60000  # milliseconds (:29)


class Batch:
    __slots__ = ("max_separation", "last_update", "points", "retries")

    def __init__(self, point: Optional[Point] = None):
        self.max_separation = 0.0
        self.last_update = 0
        # consecutive failed submit attempts (bounded-requeue budget);
        # carried in the state snapshot so a restart keeps the bound
        self.retries = 0
        self.points: List[Point] = [point] if point is not None else []

    def update(self, p: Point) -> None:
        if self.points:
            self.max_separation = max(
                self.max_separation,
                equirectangular_m(p.lat, p.lon,
                                  self.points[0].lat, self.points[0].lon))
        self.points.append(p)

    def request_body(self, uuid: str, mode: str, report_on: str,
                     transition_on: str) -> dict:
        return {
            "uuid": uuid,
            "match_options": {
                "mode": mode,
                "report_levels": [int(x) for x in report_on.split(",")],
                "transition_levels": [int(x) for x in transition_on.split(",")],
            },
            "trace": [p.to_json_obj() for p in self.points],
        }

    def request_columns(self, uuid: str, options: dict) -> tuple:
        """Columnar request part (uuid, lat, lon, time, accuracy,
        options) straight from the Point objects — the zero-dict batched
        flush path (TraceBatch.concat consumes these)."""
        pts = self.points
        n = len(pts)
        # Point stores f32-rounded lat/lon on the binary wire; the json
        # body rounds to 6 decimals identically, so columns == dict path
        lat = np.fromiter((round(float(p.lat), 6) for p in pts),
                          np.float64, n)
        lon = np.fromiter((round(float(p.lon), 6) for p in pts),
                          np.float64, n)
        tm = np.fromiter((p.time for p in pts), np.float64, n)
        acc = np.fromiter((p.accuracy for p in pts), np.float32, n)
        return (uuid, lat, lon, tm, acc, options)

    def should_report(self, min_dist: float, min_size: int,
                      min_elapsed: float) -> bool:
        return not (self.max_separation < min_dist
                    or len(self.points) < min_size
                    or self.points[-1].time - self.points[0].time
                    < min_elapsed)

    def drop(self) -> None:
        self.max_separation = 0.0
        self.points.clear()

    def apply_response(self, uuid: str,
                       response: Optional[dict]) -> Optional[dict]:
        """Trim consumed points per the response's ``shape_used``; a None
        (failed round trip) or unusable response drops the batch, like an
        unparseable response does in the reference (Batch.java:83-87)."""
        if response is None:
            logger.error("Match submit failed for %s", uuid)
            self.drop()
            return None
        try:
            sm = response.get("segment_matcher")
            # MatchRuns exposes a lazy emptiness probe; only plain-dict
            # matches (HTTP split deployments) pay the segments lookup
            has_segments = sm.has_runs() if hasattr(sm, "has_runs") \
                else bool(sm.get("segments")) if sm else False
            if "shape_used" in response:
                trim_to = response["shape_used"]
            elif has_segments:
                # segments matched but none consumed yet (the service
                # omits a falsy shape_used — reference quirk): everything
                # is still in-progress context, so keep it all. Trimming
                # to len(points) here would throw away the in-progress
                # segment AND the straddling probe the next window needs.
                trim_to = 0
            else:
                # nothing matched at all: the context is worthless;
                # consume it for forward progress (reference behavior)
                trim_to = len(self.points)
            del self.points[:trim_to]
            self.max_separation = 0.0
            pts = self.points
            if len(pts) > 1:
                # one columnar pass (reporter-lint HP001: the old
                # per-point loop re-ran scalar equirectangular_m per
                # surviving point on every trim)
                n = len(pts)
                lat = np.fromiter((p.lat for p in pts), np.float64, n)
                lon = np.fromiter((p.lon for p in pts), np.float64, n)
                self.max_separation = float(np.max(
                    equirectangular_m(lat[1:], lon[1:], lat[0], lon[0])))
            return response
        except Exception:
            self.drop()
            return None

    def report(self, uuid: str, submit: Callable[[dict], Optional[dict]],
               mode: str, report_on: str, transition_on: str,
               min_dist: float, min_size: int, min_elapsed: float
               ) -> Optional[dict]:
        """Fire a report if thresholds are met; trim consumed points."""
        if not self.should_report(min_dist, min_size, min_elapsed):
            return None
        try:
            response = submit(self.request_body(uuid, mode, report_on,
                                                transition_on))
        except Exception as e:
            # a failed round trip drops the batch, like an unparseable
            # response does in the reference
            logger.error("Match submit failed for %s: %s", uuid, e)
            self.drop()
            return None
        return self.apply_response(uuid, response)


def segments_from_response(response: Optional[dict]) -> List[Tuple[str, Segment]]:
    """datastore.reports[] -> [(key, Segment)] with validity filtering
    (reference: BatchingProcessor.java:108-141)."""
    out: List[Tuple[str, Segment]] = []
    if response is None:
        return out
    datastore = response.get("datastore")
    reports = datastore.get("reports") if datastore else None
    if reports is None:
        if response:
            logger.error("Unusable report %s", json.dumps(response)[:200])
        return out
    for entry in reports:
        try:
            seg = Segment(
                id=int(entry["id"]),
                next_id=(int(entry["next_id"])
                         if entry.get("next_id") is not None else None),
                min=float(entry["t0"]), max=float(entry["t1"]),
                length=int(entry["length"]),
                queue=int(entry["queue_length"]))
        except Exception as e:
            logger.error("Unusable reported segment pair: %s (%s)", entry, e)
            continue
        if seg.valid():
            out.append((f"{seg.id} {seg.next_id}", seg))
        else:
            logger.warning("Got back invalid segment: %s", entry)
    return out


class PointBatcher:
    """Stateful (uuid -> Batch) processor with eviction.

    ``submit`` performs the match+report round trip and returns the parsed
    response dict (or None). ``forward`` receives (key, Segment) pairs.
    """

    def __init__(self, submit: Callable[[dict], Optional[dict]],
                 forward: Callable[[str, Segment], None],
                 mode: str = "auto", report_on: str = "0,1",
                 transition_on: str = "0,1",
                 session_gap_ms: int = SESSION_GAP_MS,
                 submit_many: Optional[Callable[
                     [List[dict]], List[Optional[dict]]]] = None,
                 report_flush: int = 64,
                 retry_budget: Optional[int] = None,
                 deadletter_dir: Optional[str] = None,
                 governor: Optional[BackpressureGovernor] = None,
                 on_evict: Optional[Callable[[str], None]] = None):
        self.submit = submit
        # session-end hook: called once per uuid evicted at the session
        # gap, AFTER its final relaxed-threshold report flushed — the
        # worker wires this to the matcher's carried-state eviction so
        # incremental decode state dies with the session, not the budget
        self.on_evict = on_evict
        # batched submit for flush paths (one device batch for a whole
        # punctuate/pending flush); falls back to per-uuid submit
        self.submit_many = submit_many or (
            lambda bodies: [self._submit_safe(b) for b in bodies])
        self.forward = forward
        self.mode = mode
        self.report_on = report_on
        self.transition_on = transition_on
        self.session_gap_ms = session_gap_ms
        self.store: Dict[str, Batch] = {}
        # ONE shared match_options dict for every request this batcher
        # emits — lets the matcher resolve params once per device batch
        self.options = {
            "mode": mode,
            "report_levels": [int(x) for x in report_on.split(",")],
            "transition_levels": [int(x) for x in transition_on.split(",")],
        }
        # uuids whose batches crossed the report thresholds, awaiting the
        # next batched flush (ordered set). The reference fires one
        # matcher call per crossing (Batch.java:66-68); deferring them a
        # moment batches many sessions into one padded device decode —
        # same results, the window just extends by a few probes.
        self.pending: Dict[str, None] = {}
        self.report_flush = max(1, int(report_flush))
        # bounded requeue: how many consecutive failed submits a live
        # batch survives before its trace JSON dead-letters (the
        # reference silently dropped the batch on the FIRST failure,
        # Batch.java:83-87)
        if retry_budget is None:
            from ..utils.runtime import _env_int
            retry_budget = _env_int("REPORTER_TPU_SUBMIT_RETRIES", 2)
        self.retry_budget = max(0, retry_budget)
        # spool for exhausted batches' trace JSON (None = log-and-drop);
        # files replay by POSTing their body to any /report endpoint
        self.deadletter_dir = deadletter_dir
        self._deadletter_seq = 0
        # backpressure governor (streaming/backpressure.py): submit-
        # latency EWMA + requeue depth -> bounded offer delays and,
        # past the shed threshold, report-ready sessions dead-letter
        # instead of joining a pending set that can only grow
        self.governor = governor if governor is not None \
            else BackpressureGovernor()
        # sessions currently carrying a failed-submit retry — the
        # governor's requeue-depth sensor, maintained O(1) here instead
        # of scanned O(store) per flush
        self._retrying: Dict[str, None] = {}

    def offer_delay(self) -> float:
        """The governor's current per-offer block (the worker's offer
        loop sleeps this before accepting the next message)."""
        return self.governor.offer_delay()

    def _submit_safe(self, body) -> Optional[dict]:
        if isinstance(body, TraceView):
            body = body.to_request()  # per-trace HTTP path wants JSON
        try:
            return self.submit(body)
        except Exception as e:
            logger.error("Match submit failed for %s: %s",
                         body.get("uuid"), e)
            return None

    def _forward_all(self, response: Optional[dict]) -> int:
        n = 0
        for key, seg in segments_from_response(response):
            self.forward(key, seg)
            n += 1
        return n

    def process(self, uuid: str, point: Point, stream_time_ms: int) -> None:
        batch = self.store.pop(uuid, None)
        if batch is None:
            batch = Batch(point)
        else:
            batch.update(point)
            if batch.should_report(REPORT_DIST, REPORT_COUNT, REPORT_TIME):
                if self.governor.should_shed():
                    # backpressure past the shed threshold: the matcher
                    # cannot keep up, so a report-ready session dead-
                    # letters its trace JSON NOW (replayable, bounded
                    # by the spool cap) instead of joining a pending
                    # set that can only grow while submits fail
                    metrics.count("backpressure.shed")
                    self._retrying.pop(uuid, None)
                    self._deadletter(uuid, batch)
                    batch.drop()
                    batch.retries = 0
                    return
                # defer to the next batched flush instead of matching
                # this one session at batch=1 (the reference's only mode)
                self.pending[uuid] = None
        if batch.points:
            batch.last_update = stream_time_ms
            self.store[uuid] = batch
        if len(self.pending) >= self.report_flush:
            self.flush_pending()

    def _flush_due(self, due) -> None:
        """ONE batched submit for (uuid, batch) pairs -> forward the
        resulting segment pairs; bodies go columnar (TraceBatch), so the
        in-process service path never builds a point dict.

        Failure domain: a failed round trip (a whole-submit exception or
        a per-trace None) requeues the batch under the retry budget and
        then dead-letters it — an infrastructure hiccup must neither
        kill the stream thread nor silently lose the trace."""
        if not due:
            return
        with obs_trace.span("batcher.flush", sessions=len(due)):
            tb = TraceBatch.concat([
                batch.request_columns(uuid, self.options)
                for uuid, batch in due])
            t0 = time.monotonic()
            try:
                faults.failpoint("matcher.submit")
                responses = self.submit_many(tb)
            except Exception as e:
                logger.error("batched submit failed for %d traces: %s",
                             len(due), e)
                responses = [None] * len(due)
            elapsed = time.monotonic() - t0
            failures = 0
            for (uuid, batch), response in zip(due, responses):
                if response is None:
                    failures += 1
                    self._submit_failed(uuid, batch)
                    continue
                batch.retries = 0
                self._retrying.pop(uuid, None)
                self._forward_all(batch.apply_response(uuid, response))
            # feed the backpressure sensors AFTER the retry bookkeeping
            # so the requeue depth reflects this flush's outcome
            self.governor.note_flush(len(due), elapsed, failures,
                                     len(self._retrying))

    def _submit_failed(self, uuid: str, batch: Batch) -> None:
        """One failed round trip: requeue a live batch under the budget,
        dead-letter it (and evicted batches, which have no next flush to
        ride) once the budget is spent."""
        if self.store.get(uuid) is batch \
                and batch.retries < self.retry_budget:
            batch.retries += 1
            self.pending[uuid] = None
            self._retrying[uuid] = None
            metrics.count("batch.requeued")
            logger.warning("submit failed for %s; requeued (%d/%d)",
                           uuid, batch.retries, self.retry_budget)
            return
        metrics.count("batch.dropped")
        self._retrying.pop(uuid, None)
        self._deadletter(uuid, batch)
        batch.drop()
        # the budget is per report attempt: a session that re-qualifies
        # after this drop gets a fresh budget, not a permanent ban
        batch.retries = 0

    def _deadletter(self, uuid: str, batch: Batch) -> None:
        """Spool the batch's request JSON for replay; best-effort — the
        spool failing must not take the stream down with it."""
        if self.deadletter_dir is None or not batch.points:
            logger.error("Dropping batch for %s after %d failed submits "
                         "(%d points, no dead-letter spool)",
                         uuid, batch.retries + 1, len(batch.points))
            return
        body = batch.request_body(uuid, self.mode, self.report_on,
                                  self.transition_on)
        self._deadletter_seq += 1
        # pid-qualified: the sequence restarts with the process, and a
        # colliding name would os.replace an earlier spooled trace away
        name = f"trace-{os.getpid()}-{self._deadletter_seq:06d}" \
               f".{uuid}.json"
        try:
            # shared spool layer: atomic commit (these bodies replay
            # through the drainer / replay_cli) + the byte cap with
            # oldest-first shedding (REPORTER_TPU_DEADLETTER_MAX_MB)
            path = spool.write(self.deadletter_dir, name,
                               json.dumps(body, separators=(",", ":")))
            metrics.count("batch.deadletter")
            logger.warning("Dead-lettered trace for %s -> %s", uuid, path)
            # a dead-lettered trace means the matcher stayed down past
            # the retry budget — postmortem what was in flight
            flightrec.dump("deadletter.trace", {"uuid": uuid})
        except Exception as e:
            logger.error("Trace dead-letter spool failed for %s: %s",
                         uuid, e)

    def flush_pending(self) -> None:
        """Flush every session that crossed the report thresholds since
        the last flush through ONE device batch. Sessions evicted or
        trimmed in the meantime re-qualify on their next point."""
        if not self.pending:
            return
        due = []
        for uuid in self.pending:
            batch = self.store.get(uuid)
            if batch is not None and batch.should_report(
                    REPORT_DIST, REPORT_COUNT, REPORT_TIME):
                due.append((uuid, batch))
        self.pending.clear()
        self._flush_due(due)

    def punctuate(self, stream_time_ms: int) -> None:
        """Evict batches idle past the session gap, reporting what we can
        with relaxed thresholds (reference: BatchingProcessor.java:87-106).

        Evicted uuids AND pending mid-stream reports flush through ONE
        ``submit_many`` call, so a punctuate cycle over N sessions
        decodes as one padded device batch of N — not N batches of 1
        (the round-1..3 weakness; the reference can only do one C++ call
        per trace, Batch.java:66-68).
        """
        due = []
        evicted = []
        for uuid in list(self.store):
            batch = self.store[uuid]
            if stream_time_ms - batch.last_update > self.session_gap_ms:
                del self.store[uuid]
                self.pending.pop(uuid, None)
                # an evicted session leaves the requeue-depth sensor
                # (its dead-letter path re-accounts it if the final
                # report fails too)
                self._retrying.pop(uuid, None)
                evicted.append(uuid)
                if batch.should_report(0, 2, 0):
                    due.append((uuid, batch))
        for uuid in self.pending:  # still live, thresholds crossed
            batch = self.store.get(uuid)
            if batch is not None and batch.should_report(
                    REPORT_DIST, REPORT_COUNT, REPORT_TIME):
                due.append((uuid, batch))
        self.pending.clear()
        self._flush_due(due)
        if self.on_evict is not None:
            # after the flush: the session's FINAL report still rides
            # its carried incremental state; only then is it dropped
            for uuid in evicted:
                try:
                    self.on_evict(uuid)
                except Exception as e:
                    logger.error("on_evict failed for %s: %s", uuid, e)
