"""Segment accumulation, privacy culling, and tile egress.

The streaming analog of the reference's AnonymisingProcessor
(reference: AnonymisingProcessor.java). Semantics preserved:

- each segment observation is appended to every (time bucket, graph tile)
  slice it touches (AnonymisingProcessor.java:120-153), slices capped at
  20,000 segments (the reference's Kafka ~1MB value cap, :32-45)
- on each flush interval, slices per tile are gathered, sorted by
  (id, next_id), and runs of identical pairs shorter than the privacy
  threshold are removed (:155-175, :223-266)
- surviving tiles are written as CSV with the reference's column layout to
  S3 / HTTP POST / local files, under
  ``{t0}_{t1}/{level}/{tile_index}/{source}.{uuid4}`` (:177-220)

S3 egress uses boto3 when available (gated — this image has no network),
falling back to an error log, mirroring the reference's swallow-and-log
egress failures (HttpClient.java:95-98).
"""
from __future__ import annotations

import logging
import os
import tempfile
from typing import Dict, List, Optional

from ..core.types import Segment, TimeQuantisedTile
from ..obs import flightrec
from ..obs import trace as obs_trace
from ..utils import faults, fsio, spool
from ..utils import http as http_egress
from ..utils import locks as _locks
from ..utils import metrics

logger = logging.getLogger("reporter_tpu.streaming")

SLICE_SIZE = 20000  # reference: AnonymisingProcessor.java:45


def privacy_cull(segments: List[Segment], privacy: int) -> List[Segment]:
    """Drop runs of identical (id, next_id) pairs shorter than ``privacy``.

    Input must be sorted by (id, next_id)
    (reference: AnonymisingProcessor.java:155-175).
    """
    out: List[Segment] = []
    i = 0
    n = len(segments)
    while i < n:
        j = i
        while j < n and segments[j].sort_key() == segments[i].sort_key():
            j += 1
        if j - i >= privacy:
            out.extend(segments[i:j])
        i = j
    return out


class TileSink:
    """Where finished tiles go: file dir, http(s) endpoint, or s3 bucket
    (reference: AnonymisingProcessor.java:85-101,177-220).

    The reference swallows-and-logs egress failures (HttpClient.java:95-98)
    — a flaky endpoint silently loses tiles. Here every outcome is counted
    (``egress.ok`` / ``egress.fail`` in ``metrics.default``) and a failed
    tile body is spooled to a dead-letter directory in the same
    ``{t0}_{t1}/{level}/{tile_index}/{file}`` layout the file sink writes,
    so ``python -m reporter_tpu datastore ingest --delete <dir>`` replays
    it without loss or double counting.
    """

    def __init__(self, output: str, deadletter: Optional[str] = None):
        self.output = output.rstrip("/")
        self.is_bucket = self.output.endswith("amazonaws.com") or \
            self.output.startswith("s3://")
        self.is_http = self.output.startswith("http://") or \
            self.output.startswith("https://")
        if self.is_bucket and not (self.is_http or
                                   self.output.startswith("s3://")):
            raise ValueError(f"Cannot PUT to {output} without a scheme")
        if not self.is_bucket and not self.is_http:
            os.makedirs(self.output, exist_ok=True)
            default_dl = os.path.join(self.output, ".deadletter")
        else:
            # remote sink: spool locally at a stable ABSOLUTE path — a
            # cwd-relative default would scatter spools across launch
            # dirs (or hit an unwritable / under a service manager)
            default_dl = os.path.join(tempfile.gettempdir(),
                                      "reporter_tpu_deadletter")
        self.deadletter = deadletter if deadletter is not None else default_dl

    def store(self, tile_name: str, file_name: str, payload: str) -> bool:
        ok = False
        try:
            # failure domain: the before-hook models a sink that never
            # got the payload (error/timeout/crash); the after-hook
            # models a committed-but-unacknowledged write (kind=partial)
            # — the duplicate-risk window the epoch-stamped file names
            # (the sink idempotency key) exist to absorb
            faults.failpoint("egress.http")
            if self.is_http:
                # signed PUT for AWS endpoints, plain POST otherwise
                # (reference: AnonymisingProcessor.java:177-220)
                ok = http_egress.egress_tile(
                    self.output, tile_name + "/" + file_name, payload)
            elif self.is_bucket:  # s3:// form needs the SDK
                ok = self._store_s3(tile_name + "/" + file_name, payload)
            else:
                path = os.path.join(self.output, tile_name)
                os.makedirs(path, exist_ok=True)
                # atomic commit (reporter-lint DUR001): these files
                # carry deterministic epoch names — a torn write under
                # the final name after a crash would be "committed"
                # garbage the epoch marker then tells restore to skip
                fsio.atomic_write_text(os.path.join(path, file_name),
                                       payload)
                ok = True
            if ok:
                faults.failpoint("egress.http", after=True)
        except Exception as e:
            ok = False
            logger.error("Couldn't flush tile to sink %s/%s: %s",
                         tile_name, file_name, e)
        if ok:
            metrics.count("egress.ok")
            return True
        metrics.count("egress.fail")
        self.spool_tile(tile_name, file_name, payload)
        return False

    def spool_tile(self, tile_name: str, file_name: str, payload: str,
                   reason: str = "egress") -> None:
        """Spool a tile body for later replay. ``reason`` labels WHOSE
        failure this is — ``egress`` (this sink, the default) counts
        ``egress.deadletter``; the anonymiser passes ``tee`` for a
        datastore-tee failure with successful egress, counted
        ``datastore.tee.deadletter`` so an alert on the egress metric
        never rotates a healthy sink over a datastore fault."""
        try:
            # atomic spool (reporter-lint DUR001): a torn dead-letter
            # body would replay as a silently-truncated tile — ingest
            # drops malformed rows rather than failing the file. The
            # shared spool layer also enforces the byte cap
            # (REPORTER_TPU_DEADLETTER_MAX_MB, oldest shed first): a
            # dead sink must not fill the disk at stream rate
            spool.write(self.deadletter, tile_name + "/" + file_name,
                        payload)
            # two literal count sites, not one conditional expression:
            # the registry-drift lint attributes literal metric names
            if reason == "egress":
                metrics.count("egress.deadletter")
            else:
                metrics.count("datastore.tee.deadletter")
            logger.warning("Spooled failed tile (%s failure) to %s/%s/%s",
                           reason, self.deadletter, tile_name, file_name)
            # a tile in the spool means a consumer is failing: leave a
            # postmortem of what led up to it, naming which one
            flightrec.dump(f"deadletter.tile.{reason}",
                           {"tile": tile_name, "file": file_name})
        except Exception as e:  # spool is best-effort: never raise
            logger.error("Dead-letter spool failed for %s/%s: %s",
                         tile_name, file_name, e)

    def _store_s3(self, key: str, payload: str) -> bool:
        try:
            import boto3  # gated: not present in all deployments
        except ImportError:
            logger.error("s3:// output configured but boto3 unavailable; "
                         "use an https bucket URL for SDK-less egress")
            return False
        bucket = self.output.replace("s3://", "").split("/")[0]
        boto3.client("s3").put_object(Bucket=bucket, Key=key,
                                      Body=payload.encode())
        return True


class Anonymiser:
    """Stateful slice store + flush loop."""

    def __init__(self, sink: TileSink, privacy: int, quantisation: int,
                 mode: str = "auto", source: str = "rtpu", tee=None):
        if privacy < 1:
            raise ValueError("Need a privacy parameter of 1 or more")
        if quantisation < 60:
            raise ValueError("Need quantisation parameter of 60 or more")
        self.sink = sink
        self.privacy = privacy
        self.quantisation = quantisation
        self.mode = mode.upper()
        self.source = source
        # optional callable(tile, segments) fed every culled flush before
        # egress — the zero-serialisation hook a co-located datastore uses
        # (datastore.LocalDatastore.ingest_segments); a tee failure is
        # logged but never blocks tile egress. A tee accepting an
        # ``ingest_key`` kwarg additionally receives the flush identity
        # (the exactly-once ledger key ``{tile_name}/{file_name}`` —
        # identical to the tile file's relpath a directory replay would
        # derive), detected once here so legacy two-arg tees keep working
        self.tee = tee
        self._tee_wants_key = False
        if tee is not None:
            import inspect
            try:
                params = inspect.signature(tee).parameters.values()
                self._tee_wants_key = any(
                    p.name == "ingest_key" or p.kind == p.VAR_KEYWORD
                    for p in params)
            except (TypeError, ValueError):  # builtins/partials: legacy
                pass
        # monotonic flush epoch: stamped into every tile file name this
        # flush emits (the sink idempotency key) and carried in the
        # StateStore snapshot. The reference named files {source}.{uuid4}
        # (AnonymisingProcessor.java:209) — random names mean a crash
        # between egress and snapshot re-emits the same segments under a
        # NEW name (duplicate tiles); deterministic epoch names make the
        # re-emit overwrite byte-identically, and a committed-epoch
        # marker lets restore skip the epoch outright (state.py).
        self.flush_epoch = 0
        # optional writer id distinguishing concurrent workers sharing
        # one sink (multihost): without it two workers' epoch-0 files
        # for one tile would collide
        self.writer_id = os.environ.get("REPORTER_TPU_WRITER_ID", "")
        # tile -> current slice number; "tile.slice" -> segments
        self.slice_of: Dict[TimeQuantisedTile, int] = {}
        self.slices: Dict[str, List[Segment]] = {}

    def epoch_file_name(self, epoch: int) -> str:
        """The deterministic per-flush file name: one flush writes at
        most one file per tile dir, so source + epoch identifies it."""
        writer = f".{self.writer_id}" if self.writer_id else ""
        return f"{self.source}{writer}.e{epoch:08d}"

    # the tile map (slice_of/slices) is single-thread-owned by design:
    # the worker punctuation loop is the only writer, and the drainer
    # forwards replayed segments on that same thread. @thread_affine
    # turns a second thread slipping in (racecheck RC004) into a named
    # finding instead of a silently torn slice table.
    @_locks.thread_affine
    def process(self, key: str, segment: Segment) -> None:
        for tile in TimeQuantisedTile.tiles_for(segment, self.quantisation):
            slice_no = self.slice_of.get(tile)
            if slice_no is None:
                slice_no = 0
                self.slice_of[tile] = 0
            name = f"{tile}.{slice_no}"
            bucket = self.slices.setdefault(name, [])
            bucket.append(segment)
            if len(bucket) >= SLICE_SIZE:
                self.slice_of[tile] = slice_no + 1

    @_locks.thread_affine
    def punctuate(self) -> int:
        """Flush every tile: gather slices, sort, cull, store. Returns the
        number of tiles written. Every flush consumes one epoch (bumped
        even when nothing qualifies, so epoch numbering is a pure
        function of the punctuation sequence — deterministic replays
        stay deterministic)."""
        written = 0
        epoch = self.flush_epoch
        file_name = self.epoch_file_name(epoch)
        # the flush span carries the epoch; a tile file on disk names
        # its epoch too, so the file is traceable back to this span —
        # and through its parents to the requests that fed it
        with obs_trace.span("egress.flush", epoch=epoch):
            for tile, max_slice in list(self.slice_of.items()):
                del self.slice_of[tile]
                segments: List[Segment] = []
                for i in range(max_slice + 1):
                    name = f"{tile}.{i}"
                    part = self.slices.pop(name, None)
                    if part is not None:
                        segments.extend(part)
                    else:
                        logger.warning("Missing quantised tile slice %s",
                                       name)
                segments.sort(key=Segment.sort_key)
                before = len(segments)
                segments = privacy_cull(segments, self.privacy)
                logger.info(
                    "Anonymised quantised tile %s from %d to %d segments",
                    tile, before, len(segments))
                if not segments:
                    continue
                tile_name = "{}_{}/{}/{}".format(
                    tile.time_range_start,
                    tile.time_range_start + self.quantisation - 1,
                    tile.tile_level(), tile.tile_index())
                tee_ok = True
                if self.tee is not None:
                    try:
                        if self._tee_wants_key:
                            # the flush identity == the tile file's
                            # relpath: tee ingest and a later directory
                            # replay of the same flush derive the SAME
                            # ledger key, so they dedupe against each
                            # other (end-to-end exactly-once)
                            self.tee(tile, segments,
                                     ingest_key=f"{tile_name}/{file_name}")
                        else:
                            self.tee(tile, segments)
                    except Exception as e:
                        tee_ok = False
                        logger.error("datastore tee failed for tile %s: %s",
                                     tile, e)
                payload = "\n".join(
                    [Segment.column_layout()]
                    + [s.csv_row(self.mode, self.source) for s in segments])
                logger.info("Writing tile to %s/%s/%s with %d segments",
                            self.sink.output, tile_name, file_name,
                            len(segments))
                with obs_trace.span("egress.tile", epoch=epoch,
                                    tile=tile_name):
                    ok = self.sink.store(tile_name, file_name, payload)
                if ok:
                    written += 1
                    if not tee_ok and hasattr(self.sink, "spool_tile"):
                        # egress succeeded but the datastore ingest did
                        # not: without a spool entry this observation
                        # would live in the tile file and NEVER in the
                        # store (the loss path bigreplay's exactly-once
                        # parity check catches). Spool it so the drainer
                        # replays it into the datastore — the ledger key
                        # dedupes the already-egressed sink side.
                        # (reason="tee": a datastore fault must not be
                        # counted — or alerted — as a sink failure)
                        self.sink.spool_tile(tile_name, file_name,
                                             payload, reason="tee")
        # drop unreferenced slices (reference: :258-265)
        for name in list(self.slices):
            logger.warning("Deleting unreferenced quantised tile slice %s",
                           name)
            del self.slices[name]
        self.flush_epoch = epoch + 1
        return written
