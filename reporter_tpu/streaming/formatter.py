"""Pluggable raw-message formatter, configured by the reference's one-string
format (reference: Formatter.java:36-51 and README "Kafka-based Reporter").

The first character of the config string is the argument separator; the
first argument picks the type:

  sv:    separator regex, uuid col, lat col, lon col, time col, accuracy
         col, optional date pattern      (Formatter.java:42-44)
  json:  uuid key, lat key, lon key, time key, accuracy key, optional
         date pattern                    (Formatter.java:46-47)

Examples (from the reference README):
  ",sv,\\|,1,9,10,0,5,yyyy-MM-dd HH:mm:ss"
  "@json@id@latitude@longitude@timestamp@accuracy"

Date patterns are the Java/joda style the reference documents; the common
tokens are translated to strptime. Without a pattern, the time field is
epoch seconds.
"""
from __future__ import annotations

import calendar
import json
import math
import re
import time as _time
from typing import Optional, Tuple

from ..core.types import Point

# Java/joda date tokens -> strptime, longest first
_JAVA_TOKENS = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"),
]


def java_date_to_strptime(pattern: str) -> str:
    out = pattern
    for java, py in _JAVA_TOKENS:
        out = out.replace(java, py)
    return out


class Formatter:
    def __init__(self, kind: str, *, separator: Optional[str] = None,
                 uuid_field=None, lat_field=None, lon_field=None,
                 time_field=None, accuracy_field=None,
                 time_format: Optional[str] = None):
        if kind not in ("sv", "json"):
            raise ValueError("Unsupported raw format parser")
        self.kind = kind
        self.separator = separator
        self.uuid_field = uuid_field
        self.lat_field = lat_field
        self.lon_field = lon_field
        self.time_field = time_field
        self.accuracy_field = accuracy_field
        self.time_format = java_date_to_strptime(time_format) \
            if time_format else None

    @classmethod
    def from_config(cls, config: str) -> "Formatter":
        """Parse the one-string config (reference: Formatter.java:36-51)."""
        sep, rest = config[0], config[1:]
        args = rest.split(sep)
        if args[0] == "sv":
            return cls(
                "sv", separator=args[1],
                uuid_field=int(args[2]), lat_field=int(args[3]),
                lon_field=int(args[4]), time_field=int(args[5]),
                accuracy_field=int(args[6]),
                time_format=args[7] if len(args) > 7 else None)
        if args[0] == "json":
            return cls(
                "json",
                uuid_field=args[1], lat_field=args[2], lon_field=args[3],
                time_field=args[4], accuracy_field=args[5],
                time_format=args[6] if len(args) > 6 else None)
        raise ValueError("Unsupported raw format parser")

    def _parse_time(self, value) -> int:
        if self.time_format is not None:
            st = _time.strptime(str(value), self.time_format)
            return calendar.timegm(st)
        return int(value)

    def format(self, message: str) -> Tuple[str, Point]:
        """Raw message -> (uuid, Point); raises on unparseable input, which
        callers log and skip (reference: KeyedFormattingProcessor.java:39-41).
        """
        if self.kind == "sv":
            parts = re.split(self.separator, message.rstrip("\r\n"))
            get = lambda i: parts[i]  # noqa: E731
        else:
            node = json.loads(message)
            get = lambda k: node[k]  # noqa: E731
        lat = float(get(self.lat_field))
        lon = float(get(self.lon_field))
        tm = self._parse_time(get(self.time_field))
        accuracy = int(math.ceil(float(get(self.accuracy_field))))
        uuid = str(get(self.uuid_field))
        return uuid, Point(lat, lon, accuracy, tm)
