"""Registry-drift pass (KN/MT rules): knobs and metric names, two-sided.

The ``REPORTER_TPU_*`` env surface and the metric names on /stats are
operator API — a knob that README doesn't document is undiscoverable, a
documented knob the code no longer reads is a silent no-op at 3am, and
a renamed metric breaks every dashboard grepping the old name. Five
knobs had already drifted out of README's table when this pass landed.

All checks are TWO-SIDED against :mod:`registry` (the single source of
truth) so the lists can neither rot nor bloat:

KN001  knob drift between the code and the registry: a
       ``REPORTER_TPU_*`` name mentioned in code (any Python string
       constant, or the C++ runtime's ``getenv``) but missing from
       ``registry.ENV_KNOBS`` — or a registered knob nothing reads.
KN002  knob drift between the registry and README's knob table: a
       registered knob with no table row, or a table row for an
       unregistered knob. Rows use FULL variable names (the pre-PR 6
       ``_TRIES``-style shorthand is exactly how five knobs vanished).
MT001  a metric name passed to the metrics layer (``count``/``timer``/
       ``observe`` on a metrics registry) that no registry entry
       covers. Literal names must match an exact entry or a ``prefix.*``
       pattern; f-strings with a static prefix must match a pattern.
       Names that are dynamic from the first character (the circuit
       breaker's ``f"{self.name}.opened"``) are unresolvable and
       skipped — register the instantiated family as a pattern.
MT002  a dead exact registry entry: no string literal anywhere in the
       scanned code matches it. Pattern entries are exempt — they exist
       precisely because their call sites are dynamic.

The registry and this package are excluded from the code scans (the
registry must not witness itself).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry
from .core import Finding, SourceFile

RULES = {
    "KN001": "env knob drift between the code and the registry",
    "KN002": "env knob drift between the registry and README's table",
    "MT001": "metric name not covered by the registry",
    "MT002": "dead registry metric entry (no call site or literal)",
}

_KNOB_RE = re.compile(r"^REPORTER_TPU_[A-Z0-9_]+$")
_KNOB_TEXT_RE = re.compile(r"REPORTER_TPU_[A-Z0-9_]+")
_METRIC_SINKS = frozenset({"count", "timer", "observe"})
_METRIC_BASES = frozenset({"metrics", "registry", "_registry", "default",
                           "reg"})
#: package paths excluded from the code-side scans: the registry must
#: not be its own evidence, and fixtures aren't product code.
_SELF = "reporter_tpu/analysis/"
#: analysis/ modules that ARE product code (the runtime concurrency
#: witness emits real metrics/knob reads) — exempt from the self-skip.
_RUNTIME_IN_SELF = ("reporter_tpu/analysis/racecheck.py",)


def _self_excluded(relpath: str) -> bool:
    return relpath.startswith(_SELF) and relpath not in _RUNTIME_IN_SELF

README_KNOB_HEADER = "## Configuration knobs"


def _knob_mentions(files: Sequence[SourceFile]
                   ) -> Dict[str, Tuple[str, int]]:
    """{knob name: (relpath, line) of one mention} over every Python
    string constant in the scanned files (reads, writes, ENV_*
    constants — a mention is a mention)."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in files:
        if _self_excluded(sf.relpath):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _KNOB_RE.match(node.value):
                out.setdefault(node.value,
                               (sf.relpath, node.lineno))
    return out


def _cpp_knob_mentions(repo_root: str) -> Set[str]:
    """Knob names the C++ runtime reads (getenv in native/src)."""
    src_dir = os.path.join(repo_root, "reporter_tpu", "native", "src")
    found: Set[str] = set()
    try:
        names = sorted(os.listdir(src_dir))
    except OSError:
        return found
    for name in names:
        if not name.endswith((".cpp", ".cc", ".h", ".hpp")):
            continue
        try:
            with open(os.path.join(src_dir, name),
                      encoding="utf-8") as f:
                found.update(_KNOB_TEXT_RE.findall(f.read()))
        except OSError:
            continue
    return found


def parse_readme_knobs(readme_text: str) -> Dict[str, int]:
    """{knob name: line} from README's knob-table rows (lines starting
    with ``|`` inside the "Configuration knobs" section)."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(readme_text.splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.startswith(README_KNOB_HEADER)
            continue
        if in_section and line.lstrip().startswith("|"):
            for name in _KNOB_TEXT_RE.findall(line):
                out.setdefault(name, i)
    return out


# ---- metric call-site extraction -------------------------------------------

def _metric_name_glob(node: ast.AST) -> Optional[str]:
    """A metric-name argument as a match glob: literal strings verbatim,
    f-strings with each dynamic field collapsed to ``*`` (only when the
    leading part is static); None = unresolvable (skipped)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        if not parts or parts[0] == "*":
            return None  # dynamic from the first char: unresolvable
        glob = "".join(parts)
        while "**" in glob:
            glob = glob.replace("**", "*")
        return glob
    return None


def _metric_sites(files: Sequence[SourceFile]
                  ) -> List[Tuple[str, int, str]]:
    """(relpath, line, name-glob) for every resolvable metric-name
    argument at a metrics-layer call site."""
    out: List[Tuple[str, int, str]] = []
    for sf in files:
        if _self_excluded(sf.relpath):
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_SINKS):
                continue
            base = node.func.value
            base_name = base.attr if isinstance(base, ast.Attribute) \
                else base.id if isinstance(base, ast.Name) else None
            if base_name not in _METRIC_BASES:
                continue
            if not node.args:
                continue
            glob = _metric_name_glob(node.args[0])
            if glob is not None:
                out.append((sf.relpath, node.lineno, glob))
    return out


def _covered(glob: str, metrics_reg: Dict[str, str]) -> bool:
    """Is a call-site name glob covered by the registry? A literal name
    must equal an exact entry or extend a ``prefix.*`` pattern (a
    truncated literal that merely prefixes a pattern is a typo, not
    covered); an f-string glob's static prefix must be compatible with
    a pattern (either side extending the other) or with an exact entry
    it prefixes."""
    if "*" not in glob:
        if glob in metrics_reg:
            return True
        return any(entry.endswith("*") and glob.startswith(entry[:-1])
                   for entry in metrics_reg)
    prefix = glob.split("*", 1)[0]
    for entry in metrics_reg:
        if entry.endswith("*"):
            ep = entry[:-1]
            if prefix.startswith(ep) or ep.startswith(prefix):
                return True
        elif entry.startswith(prefix):
            return True
    return False


def _string_literals(files: Sequence[SourceFile]) -> Set[str]:
    out: Set[str] = set()
    for sf in files:
        if _self_excluded(sf.relpath):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                out.add(node.value)
    return out


def _registry_lines(repo_root: str) -> Dict[str, int]:
    """{entry string: line in registry.py} so registry-side findings
    point at the entry to delete/fix."""
    path = os.path.join(repo_root, "reporter_tpu", "analysis",
                        "registry.py")
    out: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.setdefault(node.value, node.lineno)
    return out


REGISTRY_REL = "reporter_tpu/analysis/registry.py"
README_REL = "README.md"


def run(files: Sequence[SourceFile], repo_root: str,
        knobs: Optional[Dict[str, str]] = None,
        metrics_reg: Optional[Dict[str, str]] = None,
        readme_text: Optional[str] = None,
        full_scope: bool = True) -> List[Finding]:
    """``full_scope=False`` (a partial / fixture run) checks only the
    code -> registry direction: the reverse directions (dead knobs, dead
    metrics, README drift) need the whole package in view."""
    knobs = dict(registry.ENV_KNOBS if knobs is None else knobs)
    metrics_reg = dict(registry.METRICS if metrics_reg is None
                       else metrics_reg)
    if readme_text is None:
        try:
            with open(os.path.join(repo_root, "README.md"),
                      encoding="utf-8") as f:
                readme_text = f.read()
        except OSError:
            readme_text = ""
    reg_lines = _registry_lines(repo_root)
    findings: List[Finding] = []

    # ---- KN001: code <-> registry ------------------------------------------
    mentions = _knob_mentions(files)
    for name in sorted(mentions):
        if name not in knobs:
            rel, line = mentions[name]
            findings.append(Finding(
                rel, line, "KN001",
                f"{name} is read/set here but not in "
                f"registry.ENV_KNOBS — register it (and add a README "
                "knob-table row)"))
    if full_scope:
        cpp = _cpp_knob_mentions(repo_root)
        for name in sorted(knobs):
            if name not in mentions and name not in cpp:
                findings.append(Finding(
                    REGISTRY_REL, reg_lines.get(name, 1), "KN001",
                    f"registered knob {name} is mentioned nowhere in "
                    "the code — dead entry, remove it"))

    # ---- KN002: registry <-> README table ----------------------------------
    if full_scope:
        table = parse_readme_knobs(readme_text)
        for name in sorted(knobs):
            if name not in table:
                findings.append(Finding(
                    REGISTRY_REL, reg_lines.get(name, 1), "KN002",
                    f"registered knob {name} has no row in README's "
                    "knob table — document it (full variable name)"))
        for name in sorted(table):
            if name not in knobs:
                findings.append(Finding(
                    README_REL, table[name], "KN002",
                    f"README documents {name} but it is not in "
                    "registry.ENV_KNOBS — stale doc or missing "
                    "registration"))

    # ---- MT001: call sites -> registry -------------------------------------
    for rel, line, glob in _metric_sites(files):
        if not _covered(glob, metrics_reg):
            findings.append(Finding(
                rel, line, "MT001",
                f"metric name {glob!r} is not covered by "
                "registry.METRICS — register it (exact, or a "
                "'prefix.*' pattern for dynamic families)"))

    # ---- MT002: registry -> code literals ----------------------------------
    if full_scope:
        literals = _string_literals(files)
        for entry in sorted(metrics_reg):
            if entry.endswith("*"):
                continue  # dynamic family: call sites are f-strings
            if entry not in literals:
                findings.append(Finding(
                    REGISTRY_REL, reg_lines.get(entry, 1), "MT002",
                    f"registry metric {entry!r} matches no string "
                    "literal in the code — dead entry, remove it"))

    return findings
