"""Lock-graph pass (LD002-LD003): whole-package lock acquisition order.

PR 2's LD001 checks that guarded state is guarded everywhere — a
per-write property. What it cannot see is the *relationship between
locks*: two threads acquiring the same two locks in opposite orders
deadlock, and a lock held across a blocking call (an HTTP round trip, a
subprocess, a ctypes ``rt_*`` native) turns one slow dependency into a
process-wide stall. Both classes are exactly what the ROADMAP's
multi-process directions (pre-fork service mode, cross-process writer
lease) will amplify from "latent" to "nightly pager".

The pass builds a static lock-acquisition graph over the whole scanned
package: a node per lock — identified ``(module, owner, attr)``, where
owner is the class for ``self._lock`` attributes and the module for
globals — and an edge A -> B when a ``with B`` runs (or a function that
acquires B is called) while A is held. Call edges resolve through the
package's own functions: same-class methods first, then same-module
functions, then a package-wide unique name; ambiguous names are not
followed, and nested defs are folded into their enclosing function
(documented approximations — both err toward missing an edge, never
toward inventing one).

Callbacks handed to ``executor.submit(fn, ...)`` and
``Thread(target=fn)`` resolve like direct calls (the pre-ISSUE 10
LD002/LD003 blind spot): the callback runs on another thread, but a
caller that holds a lock at the submit site is coupled to everything
the callback acquires — the idiom is submit-then-``result()``/
``join()``, and even without the join the callback's acquisitions
order against the held lock whenever the pool runs it before the
holder releases. Lambdas and non-name callbacks are not followed
(same err-toward-missing rule as ambiguous calls).

LD002  cycle in the lock-acquisition graph: some execution order of the
       involved threads deadlocks. Reported once per cycle, at the
       acquisition site that closes it.
LD003  blocking call reachable while a lock is held: HTTP egress
       (``urlopen``, ``http_egress.post/put/egress_tile``), subprocess
       spawns, ctypes ``rt_*`` natives. The native-init race PR 2 fixed
       was this class; a documented once-only init hold (the native
       build lock) suppresses with a reason.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted, terminal_name
from .locks import _is_lock_expr

RULES = {
    "LD002": "lock-acquisition cycle (potential deadlock)",
    "LD003": "lock held across a blocking call (HTTP/subprocess/native)",
}

#: call shapes that block: unambiguous terminal names, and dotted
#: suffixes for the short ones (a bare ``post`` would match JSON
#: helpers; ``http_egress.post`` is the egress module's).
_BLOCKING_TERMINAL = frozenset({"urlopen", "check_call", "check_output",
                                "Popen", "egress_tile", "aws_put"})
_BLOCKING_DOTTED = (
    "subprocess.run", "http_egress.post", "http_egress.put",
    "requests.get", "requests.post", "requests.put",
)


def _callback_name(call: ast.Call) -> Optional[str]:
    """The terminal name of a callback handed to ``<pool>.submit(fn,
    ...)`` or ``Thread(target=fn)`` — the call shapes that move work to
    another thread. None for lambdas/partials/non-name callbacks (not
    followed; errs toward missing an edge)."""
    leaf = terminal_name(call.func)
    if leaf == "submit" and isinstance(call.func, ast.Attribute) \
            and call.args:
        return terminal_name(call.args[0])
    if leaf == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return terminal_name(kw.value)
    return None


def _is_blocking(call: ast.Call) -> Optional[str]:
    leaf = terminal_name(call.func)
    if leaf is None:
        return None
    if leaf.startswith("rt_"):
        return f"ctypes native {leaf}()"
    if leaf in _BLOCKING_TERMINAL:
        return f"{leaf}()"
    d = dotted(call.func)
    if d is not None:
        for suffix in _BLOCKING_DOTTED:
            if d == suffix or d.endswith("." + suffix):
                return f"{suffix}()"
    return None


#: ubiquitous stdlib protocol names (containers, files, queues,
#: threads, futures): ``obj.append(...)``'s receiver is almost never
#: package code, so a package function that happens to share the name
#: (``HistogramStore.append``, ``http.put``) must not be resolved as
#: the callee through the package-wide-unique fallback. Same-class and
#: same-module resolution still apply — only the fallback is barred
#: (errs toward missing an edge, like every approximation here).
_COMMON_METHODS = frozenset({
    "append", "appendleft", "extend", "add", "insert", "remove",
    "discard", "pop", "popitem", "clear", "update", "setdefault",
    "get", "put", "get_nowait", "put_nowait", "close", "open", "read",
    "write", "flush", "seek", "join", "start", "run", "send", "recv",
    "acquire", "release", "wait", "notify", "notify_all", "set",
    "result", "cancel", "shutdown", "submit", "sort", "reverse",
    "index", "copy", "items", "keys", "values",
})

LockId = Tuple[str, str, str]  # (relpath, owner, attr)


def _fmt_lock(lock: LockId) -> str:
    rel, owner, attr = lock
    mod = rel.rsplit("/", 1)[-1]
    return f"{mod}:{owner}.{attr}" if owner != "<module>" \
        else f"{mod}:{attr}"


def _lock_id(expr: ast.AST, relpath: str,
             cls: Optional[str]) -> Optional[LockId]:
    node = expr.func if isinstance(expr, ast.Call) else expr
    name = terminal_name(node)
    if name is None:
        return None
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return (relpath, cls or "<class>", name)
    if isinstance(node, ast.Name):
        return (relpath, "<module>", name)
    return (relpath, "<attr>", name)  # foreign chains: keyed by attr


class _FuncInfo:
    """What one function does, lock-wise. Nested defs are folded in."""

    __slots__ = ("key", "relpath", "cls", "acquires", "held_calls",
                 "held_locks", "held_blocking", "all_calls",
                 "all_blocking", "local_names")

    def __init__(self, key: str, relpath: str, cls: Optional[str]):
        self.key = key
        self.relpath = relpath
        self.cls = cls
        self.acquires: List[Tuple[LockId, int]] = []
        # (held lock, with-line, callee terminal name)
        self.held_calls: List[Tuple[LockId, int, str]] = []
        # (held lock, with-line, nested lock)
        self.held_locks: List[Tuple[LockId, int, LockId]] = []
        # (held lock, with-line, blocking description) — direct
        self.held_blocking: List[Tuple[LockId, int, str]] = []
        # every terminal call name / blocking description anywhere in
        # the function body (the closure edge lists)
        self.all_calls: Set[str] = set()
        self.all_blocking: Set[str] = set()
        self.local_names: Set[str] = set()


class _Collector(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.funcs: Dict[str, _FuncInfo] = {}
        self._cls: List[str] = []
        self._fn: List[_FuncInfo] = []
        self._held: List[Tuple[LockId, int]] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self._fn:  # nested def: fold into the enclosing function —
            # but its body does NOT run at def time, so it must not see
            # the def site's held-lock stack (a closure defined under a
            # lock and called later is not a held blocking call)
            self._fn[-1].local_names.add(node.name)
            held, self._held = self._held, []
            for stmt in node.body:
                self.visit(stmt)
            self._held = held
            return
        cls = self._cls[-1] if self._cls else None
        key = ".".join(self._cls + [node.name])
        info = _FuncInfo(key, self.sf.relpath, cls)
        self.funcs[key] = info
        self._fn.append(info)
        for stmt in node.body:
            self.visit(stmt)
        self._fn.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        if not self._fn:
            return
        info = self._fn[-1]
        entered = 0
        for item in node.items:
            expr = item.context_expr
            is_lock = _is_lock_expr(expr) or (
                isinstance(expr, ast.Call) and _is_lock_expr(expr.func))
            if is_lock:
                lock = _lock_id(expr, info.relpath, info.cls)
                if lock is not None:
                    for held, line in self._held:
                        info.held_locks.append((held, line, lock))
                    info.acquires.append((lock, node.lineno))
                    self._held.append((lock, node.lineno))
                    entered += 1
            else:
                self.visit(expr)  # non-lock items evaluate while held
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(entered):
            self._held.pop()

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        if self._fn:
            info = self._fn[-1]
            leaf = terminal_name(node.func)
            desc = _is_blocking(node)
            if leaf is not None:
                info.all_calls.add(leaf)
            if desc is not None:
                info.all_blocking.add(desc)
            for held, line in self._held:
                if desc is not None:
                    info.held_blocking.append((held, line, desc))
                elif leaf is not None:
                    info.held_calls.append((held, line, leaf))
            # executor.submit / Thread(target=...) callbacks: resolve
            # the handed function like a direct call, so locks it
            # acquires (and blocking work it does) are no longer
            # invisible to the graph just because a pool runs them
            cb = _callback_name(node)
            if cb is not None:
                info.all_calls.add(cb)
                for held, line in self._held:
                    info.held_calls.append((held, line, cb))
        self.generic_visit(node)


class _Resolver:
    """Callee name -> _FuncInfo across the package, scope-preferring."""

    def __init__(self, by_file: Dict[str, Dict[str, _FuncInfo]]):
        self.by_file = by_file
        self.by_name: Dict[str, List[_FuncInfo]] = {}
        for funcs in by_file.values():
            for info in funcs.values():
                self.by_name.setdefault(
                    info.key.rsplit(".", 1)[-1], []).append(info)

    def resolve(self, caller: _FuncInfo,
                name: str) -> Optional[_FuncInfo]:
        if name in caller.local_names:
            return None  # already folded into the caller
        if caller.cls is not None:
            got = self.by_file[caller.relpath].get(f"{caller.cls}.{name}")
            if got is not None:
                return got
        got = self.by_file[caller.relpath].get(name)
        if got is not None:
            return got
        if name in _COMMON_METHODS:
            return None  # stdlib protocol name: receiver is foreign
        everywhere = self.by_name.get(name, [])
        if len(everywhere) == 1:
            return everywhere[0]
        return None  # ambiguous or foreign: not followed


def _closure(info: _FuncInfo, resolver: _Resolver,
             cache: Dict[str, Tuple[Set[LockId], Set[str]]],
             stack: Set[str]) -> Tuple[Set[LockId], Set[str]]:
    """(locks acquired, blocking descriptions) reachable from ``info``
    through package-resolvable calls, cycle-safe."""
    fid = f"{info.relpath}::{info.key}"
    if fid in cache:
        return cache[fid]
    if fid in stack:
        return set(), set()
    stack.add(fid)
    locks = {lock for lock, _ in info.acquires}
    blocking = set(info.all_blocking)
    for name in sorted(info.all_calls):
        callee = resolver.resolve(info, name)
        if callee is not None and callee is not info:
            cl, cb = _closure(callee, resolver, cache, stack)
            locks |= cl
            blocking |= cb
    stack.discard(fid)
    cache[fid] = (locks, blocking)
    return cache[fid]


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    by_file: Dict[str, Dict[str, _FuncInfo]] = {}
    for sf in files:
        c = _Collector(sf)
        c.visit(sf.tree)
        by_file[sf.relpath] = c.funcs

    resolver = _Resolver(by_file)
    cache: Dict[str, Tuple[Set[LockId], Set[str]]] = {}

    edges: Dict[LockId, Set[LockId]] = {}
    edge_sites: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    findings: List[Finding] = []

    for sf in files:
        for info in by_file[sf.relpath].values():
            for held, line, nested in info.held_locks:
                if nested != held:
                    edges.setdefault(held, set()).add(nested)
                    edge_sites.setdefault((held, nested),
                                          (sf.relpath, line))
            for held, line, desc in info.held_blocking:
                findings.append(Finding(
                    sf.relpath, line, "LD003",
                    f"lock {_fmt_lock(held)} is held across blocking "
                    f"call {desc} — a stall there stalls every waiter"))
            reported: Set[Tuple[LockId, int, str]] = set()
            for held, line, name in info.held_calls:
                callee = resolver.resolve(info, name)
                if callee is None:
                    continue
                cl, cb = _closure(callee, resolver, cache, set())
                for lock in cl:
                    if lock != held:
                        edges.setdefault(held, set()).add(lock)
                        edge_sites.setdefault((held, lock),
                                              (sf.relpath, line))
                for desc in sorted(cb):
                    key = (held, line, desc)
                    if key in reported:
                        continue
                    reported.add(key)
                    findings.append(Finding(
                        sf.relpath, line, "LD003",
                        f"lock {_fmt_lock(held)} is held across "
                        f"blocking call {desc} (via {name}()) — a "
                        "stall there stalls every waiter"))

    # cycle detection (DFS from every node; each cycle reported once)
    seen_cycles: Set[Tuple[LockId, ...]] = set()
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start:
                    cyc = tuple(sorted(path))
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    rel, line = edge_sites[(node, start)]
                    order = " -> ".join(_fmt_lock(p) for p in path)
                    findings.append(Finding(
                        rel, line, "LD002",
                        f"lock-acquisition cycle {order} -> "
                        f"{_fmt_lock(start)} — opposite-order callers "
                        "deadlock"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    return findings
