"""Lock-discipline pass (LD rules).

The streaming worker, the /report service and the metrics registry share
mutable state across threads (handler pool, dispatch loop, device lanes,
native build lock). The convention is simple — state that a ``with
<lock>`` block protects anywhere must be protected *everywhere* it is
written — and until now it was only a convention. One unlocked write next
to a locked one is exactly the race that "tolerated by convention"
becomes a corrupted counter or a half-initialised handle under load.

LD001  instance attribute or module global written both inside and
       outside ``with <lock>`` blocks. Writes in ``__init__`` are
       construction (single-threaded by contract) and do not count as
       unguarded sites; a name is "lock-like" when its last path segment
       matches ``lock``/``mutex``/``mu`` (``self._lock``,
       ``_build_lock``, ``stripe.mu`` ...).

The pass runs on the declared threaded module set only — single-threaded
modules mixing locked and unlocked writes are not a race (and GIL-
tolerated lock-free designs like graph/route.RouteCache stay out of
scope by the same declaration).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Set, Tuple

from .core import Finding, SourceFile, terminal_name

RULES = {
    "LD001": "shared state written both inside and outside a lock",
}

#: the declared threaded module set: everything with threads or shared
#: process-global state reachable from multiple threads.
THREADED_PREFIXES = (
    "reporter_tpu/streaming/",
    "reporter_tpu/service/",
    "reporter_tpu/utils/metrics.py",
    "reporter_tpu/utils/runtime.py",
    "reporter_tpu/utils/faults.py",
    "reporter_tpu/utils/circuit.py",
    "reporter_tpu/native/__init__.py",
    # span contexts / the flight-recorder ring are touched from every
    # serving thread
    "reporter_tpu/obs/",
)

_LOCKISH = re.compile(r"(^|_)(lock|mutex|mu)s?$", re.IGNORECASE)

_MUTATORS = frozenset({
    "append", "extend", "add", "update", "clear", "pop", "popitem",
    "remove", "discard", "insert", "setdefault", "appendleft",
    "move_to_end", "sort", "reverse",
})

_CONSTRUCTORS = ("__init__", "__new__", "__post_init__")


def _is_lock_expr(node: ast.AST) -> bool:
    name = terminal_name(node)
    return bool(name and _LOCKISH.search(name))


class _Site:
    __slots__ = ("line", "locked", "in_ctor", "func")

    def __init__(self, line: int, locked: bool, in_ctor: bool, func: str):
        self.line = line
        self.locked = locked
        self.in_ctor = in_ctor
        self.func = func


class _Visitor(ast.NodeVisitor):
    """Collects write sites per (owner, attribute) where owner is a class
    (instance attributes via ``self``) or the module (globals)."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # (owner, attr) -> [sites]
        self.writes: Dict[Tuple[str, str], List[_Site]] = {}
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []
        self._lock_depth = 0
        self._globals_declared: List[Set[str]] = []
        self.module_names: Set[str] = {
            t.id
            for node in sf.tree.body if isinstance(node, ast.Assign)
            for t in node.targets if isinstance(t, ast.Name)
        } | {
            node.target.id
            for node in sf.tree.body if isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
        }

    # -- scope tracking ----------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self._globals_declared.append(set())
        self.generic_visit(node)
        self._globals_declared.pop()
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Global(self, node: ast.Global) -> None:
        if self._globals_declared:
            self._globals_declared[-1].update(node.names)

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_lock_expr(item.context_expr)
                     or (isinstance(item.context_expr, ast.Call)
                         and _is_lock_expr(item.context_expr.func))
                     for item in node.items)
        for item in node.items:
            self.visit(item)
        if locked:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self._lock_depth -= 1

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    # -- write collection --------------------------------------------------
    def _record(self, owner: str, attr: str, line: int) -> None:
        in_ctor = bool(self._func_stack) \
            and self._func_stack[0] in _CONSTRUCTORS
        func = ".".join(self._class_stack + self._func_stack) or "<module>"
        self.writes.setdefault((owner, attr), []).append(
            _Site(line, self._lock_depth > 0, in_ctor, func))

    def _owner_attr(self, target: ast.AST):
        """(owner, attr) for a write target, descending through
        subscripts: ``self.x[k] = v`` writes ``self.x``."""
        was_subscript = False
        while isinstance(target, ast.Subscript):
            was_subscript = True
            target = target.value
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and self._class_stack:
            return self._class_stack[-1], target.attr
        if isinstance(target, ast.Name) and self._func_stack:
            if self._globals_declared \
                    and target.id in self._globals_declared[-1]:
                return "<module>", target.id
            if was_subscript and target.id in self.module_names:
                # item assignment mutates the module-level container even
                # without a ``global`` declaration
                return "<module>", target.id
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            oa = self._owner_attr(t)
            if oa:
                self._record(*oa, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            oa = self._owner_attr(node.target)
            if oa:
                self._record(*oa, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        oa = self._owner_attr(node.target)
        if oa:
            self._record(*oa, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # mutating method on self.X or a module-level container:
        # self.store.pop(...), pending.clear(), ...
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            base = func.value
            oa = None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and self._class_stack:
                oa = (self._class_stack[-1], base.attr)
            elif isinstance(base, ast.Name) and self._func_stack \
                    and base.id in self.module_names:
                oa = ("<module>", base.id)
            if oa:
                self._record(*oa, node.lineno)
        self.generic_visit(node)


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not sf.relpath.startswith(THREADED_PREFIXES):
            continue
        v = _Visitor(sf)
        v.visit(sf.tree)
        for (owner, attr), sites in sorted(v.writes.items()):
            locked = [s for s in sites if s.locked]
            unlocked = [s for s in sites if not s.locked and not s.in_ctor]
            if locked and unlocked:
                where = "self." if owner != "<module>" else ""
                for s in unlocked:
                    findings.append(Finding(
                        sf.relpath, s.line, "LD001",
                        f"{where}{attr} is written under a lock elsewhere "
                        f"(e.g. line {locked[0].line}) but not here in "
                        f"{s.func} — every write needs the lock"))
    return findings
