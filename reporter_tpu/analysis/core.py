"""Shared machinery for the reporter-lint passes.

Every pass consumes :class:`SourceFile` objects (parsed once, shared) and
emits :class:`Finding` rows rendered ``path:line: RULE-ID message`` — the
grep-able contract the driver, the baseline file and CI all speak.

Suppression: a ``# lint: ignore[RULE-ID]`` comment on the flagged line or
the line directly above silences that rule there (comma-separate several
ids; ``*`` silences every rule). Suppressions are for *documented* false
positives — the comment next to them should say why.

Baseline: a committed text file of rendered findings (one per line, ``#``
comments allowed). The driver fails on findings missing from the baseline
AND on baseline entries that no longer fire (stale entries would silently
mask a future regression at the same site).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*ignore\[([A-Za-z0-9_\-,\s\*]+)\]")


@dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic: ``path:line: rule message``. ``path`` is repo-relative
    with forward slashes so renderings are stable across hosts."""

    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class SourceFile:
    """A parsed Python source file plus its suppression map."""

    path: str          # absolute
    relpath: str       # repo-relative, forward slashes
    text: str
    tree: ast.AST
    # line -> set of rule ids suppressed on that line ("*" = all)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str, repo_root: str) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            text = f.read()
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        tree = ast.parse(text, filename=rel)
        return cls(path=path, relpath=rel, text=text, tree=tree,
                   suppressions=parse_suppressions(text))

    def suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            rules = self.suppressions.get(ln)
            if rules and (rule in rules or "*" in rules):
                return True
        return False


def parse_suppressions(text: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def filter_suppressed(findings: Iterable[Finding],
                      files: Sequence[SourceFile]) -> List[Finding]:
    by_rel = {f.relpath: f for f in files}
    kept = []
    for fnd in findings:
        sf = by_rel.get(fnd.path)
        if sf is not None and sf.suppressed(fnd.rule, fnd.line):
            continue
        kept.append(fnd)
    return kept


def collect_py_files(repo_root: str,
                     roots: Optional[Sequence[str]] = None
                     ) -> List[SourceFile]:
    """Parse every .py under ``roots`` (default: the reporter_tpu package).
    Explicit file paths are accepted alongside directories."""
    if not roots:
        roots = [os.path.join(repo_root, "reporter_tpu")]
    paths: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            paths.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    paths.append(os.path.join(dirpath, name))
    return [SourceFile.load(p, repo_root) for p in sorted(set(paths))]


# ---- baseline --------------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    entries = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                entries.append(line)
    return entries


def compare_baseline(findings: Sequence[Finding],
                     baseline: Sequence[str]
                     ) -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline entries)."""
    rendered = [f.render() for f in findings]
    have = set(rendered)
    base = set(baseline)
    new = [f for f, r in zip(findings, rendered) if r not in base]
    stale = [b for b in baseline if b not in have]
    return new, stale


# ---- small AST helpers shared by the passes --------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last identifier of a Name/Attribute chain ('self._lock' -> '_lock')."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None
