"""ctypes <-> C++ ABI cross-check (ABI rules).

``native/__init__.py`` calls ``host_runtime.cpp`` through a hand-written
flat C ABI: every ``lib.rt_*.argtypes`` list must mirror the C
signature's arity, order and widths exactly, and the ``ABI_VERSION``
handshake constant must equal ``rt_abi_version()``'s return. ctypes
checks none of this — a drifted binding passes the wrong argument list
and corrupts memory (the round-2 snapshot segfault). The handshake
catches *half-landed* changes (library and binding from different
commits); this pass catches the other half: both sides landed in one
commit, wrong.

The C side is parsed with a deliberately narrow grammar (the ``rt_*``
export style host_runtime.cpp actually uses); the Python side by AST,
resolving the ndpointer aliases (``c_i32p`` ...) and ``ctypes.POINTER``
wrappers.

ABI001  export/binding missing on one side
ABI002  argument-count mismatch
ABI003  argument type/order mismatch at a position
ABI004  ABI_VERSION constant != rt_abi_version() return
ABI005  return-type mismatch (an unset restype on a void function is
        accepted: ctypes' default c_int return is ignored by callers)

Width model: pointers match on pointee width (f64*, i32*, ...); the
8-bit class is one width (``uint8_t*`` binds as either ``c_char_p`` for
bytes or an ndpointer(uint8) for arrays); ``void*`` matches only
``c_void_p``. Scalars must match exactly.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

RULES = {
    "ABI001": "native export/binding missing on one side",
    "ABI002": "argtypes arity differs from the C signature",
    "ABI003": "argtype width/order differs from the C signature",
    "ABI004": "ABI_VERSION constant differs from rt_abi_version()",
    "ABI005": "restype differs from the C return type",
}

DEFAULT_CPP = "reporter_tpu/native/src/host_runtime.cpp"
DEFAULT_PY = "reporter_tpu/native/__init__.py"

# (kind, width): kind 'ptr' | 'val'; width 'f64' 'f32' 'i64' 'i32' 'u16'
# 'u8' 'i8' 'void'
CType = Tuple[str, str]

_C_WIDTHS = {
    "double": "f64", "float": "f32", "int64_t": "i64", "int32_t": "i32",
    "uint16_t": "u16", "uint8_t": "u8", "char": "i8", "void": "void",
    "int": "i32", "long": "i64", "size_t": "i64", "uint32_t": "i32",
    "uint64_t": "i64", "int8_t": "i8", "bool": "u8",
}

# longer alternatives first (int64_t before int, etc.); any type may
# carry a pointer star so typed-pointer returns stay visible to ABI001
_SIG_RE = re.compile(
    r"^\s*(?:const\s+)?"
    r"((?:int64_t|int32_t|int8_t|uint64_t|uint32_t|uint16_t|uint8_t"
    r"|size_t|long|int|double|float|bool|char|void)\s*\*?)"
    r"\s*(rt_\w+)\s*\(([^;{)]*)\)\s*\{",
    re.MULTILINE)

_VERSION_RE = re.compile(
    r"rt_abi_version\s*\(\s*void\s*\)\s*\{\s*return\s+(\d+)\s*;")

_NDP_DTYPES = {
    "float64": "f64", "float32": "f32", "int64": "i64", "int32": "i32",
    "uint16": "u16", "uint8": "u8", "int8": "i8", "float16": "u16",
}

_CTYPES_SCALARS = {
    "c_double": ("val", "f64"), "c_float": ("val", "f32"),
    "c_int64": ("val", "i64"), "c_int32": ("val", "i32"),
    "c_int": ("val", "i32"), "c_uint8": ("val", "u8"),
    "c_uint16": ("val", "u16"), "c_int8": ("val", "i8"),
    "c_longlong": ("val", "i64"), "c_size_t": ("val", "i64"),
    "c_bool": ("val", "u8"),
    "c_void_p": ("ptr", "void"), "c_char_p": ("ptr", "i8"),
}


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.DOTALL)
    return re.sub(r"//[^\n]*", "", text)


def _parse_c_arg(raw: str) -> Optional[CType]:
    raw = raw.strip()
    if not raw or raw == "void":
        return None
    is_ptr = "*" in raw
    tokens = [t for t in re.split(r"[\s\*]+", raw)
              if t and t not in ("const", "restrict", "volatile", "struct")]
    # drop the parameter name (last token unless it is itself the type)
    type_tokens = [t for t in tokens if t in _C_WIDTHS]
    if not type_tokens:
        return ("val", f"?{raw}")
    width = _C_WIDTHS[type_tokens[0]]
    return ("ptr" if is_ptr else "val", width)


def parse_cpp(text: str) -> Tuple[Dict[str, Tuple[CType, List[CType]]],
                                  Optional[int]]:
    """{export name: (return, [args])}, abi version."""
    text = _strip_comments(text)
    out: Dict[str, Tuple[CType, List[CType]]] = {}
    for m in _SIG_RE.finditer(text):
        ret_raw, name, args_raw = m.groups()
        ret_width = _C_WIDTHS[ret_raw.replace("*", "").strip()]
        ret = ("ptr", ret_width) if "*" in ret_raw \
            else ("val", ret_width)
        args: List[CType] = []
        if args_raw.strip() and args_raw.strip() != "void":
            for part in args_raw.split(","):
                a = _parse_c_arg(part)
                if a is not None:
                    args.append(a)
        out[name] = (ret, args)
    vm = _VERSION_RE.search(text)
    return out, (int(vm.group(1)) if vm else None)


# ---- Python (ctypes) side --------------------------------------------------

def _ndpointer_width(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    d = _last_attr(call.args[0])
    return _NDP_DTYPES.get(d or "")


def _last_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _classify_py(node: ast.AST, aliases: Dict[str, CType]) -> CType:
    """ctypes argtype expression -> (kind, width); unknown -> ('?', repr)."""
    if isinstance(node, ast.Name):
        got = aliases.get(node.id)
        if got is not None:
            return got
        got = _CTYPES_SCALARS.get(node.id)
        if got is not None:
            return got
        return ("?", node.id)
    if isinstance(node, ast.Attribute):
        got = _CTYPES_SCALARS.get(node.attr)
        if got is not None:
            return got
        return ("?", node.attr)
    if isinstance(node, ast.Call):
        leaf = _last_attr(node.func)
        if leaf == "POINTER" and node.args:
            inner = _classify_py(node.args[0], aliases)
            return ("ptr", inner[1])
        if leaf == "ndpointer":
            w = _ndpointer_width(node)
            if w:
                return ("ptr", w)
        return ("?", ast.dump(node)[:40])
    if isinstance(node, ast.Constant) and node.value is None:
        return ("val", "void")  # restype = None: explicit void
    return ("?", type(node).__name__)


class _PyBindings(ast.NodeVisitor):
    """argtypes/restype assignments + ABI_VERSION from the binding module."""

    def __init__(self):
        self.aliases: Dict[str, CType] = {}
        self.argtypes: Dict[str, Tuple[int, List[CType]]] = {}
        self.restype: Dict[str, Tuple[int, CType]] = {}
        self.version: Optional[int] = None
        self.version_line = 0

    def visit_Assign(self, node: ast.Assign) -> None:
        target = node.targets[0] if len(node.targets) == 1 else None
        # alias definitions: c_i32p = np.ctypeslib.ndpointer(np.int32, ...)
        # and i64ref = ctypes.POINTER(ctypes.c_int64)
        if isinstance(target, ast.Name):
            if isinstance(node.value, ast.Call):
                got = _classify_py(node.value, self.aliases)
                if got[0] != "?":
                    self.aliases[target.id] = got
            elif target.id == "ABI_VERSION" \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                self.version = node.value.value
                self.version_line = node.lineno
        # lib.rt_x.argtypes = [...] / lib.rt_x.restype = ...
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Attribute) \
                and target.value.attr.startswith("rt_"):
            fname = target.value.attr
            if target.attr == "argtypes":
                elems = node.value.elts \
                    if isinstance(node.value, (ast.List, ast.Tuple)) else []
                self.argtypes[fname] = (
                    node.lineno,
                    [_classify_py(e, self.aliases) for e in elems])
            elif target.attr == "restype":
                self.restype[fname] = (
                    node.lineno, _classify_py(node.value, self.aliases))
        self.generic_visit(node)


def _compatible(c: CType, py: CType) -> bool:
    ck, cw = c
    pk, pw = py
    if ck != pk:
        return False
    if ck == "ptr":
        eight_bit = {"i8", "u8"}
        if cw in eight_bit and pw in eight_bit:
            return True
    return cw == pw


def _fmt(t: CType) -> str:
    kind, width = t
    return f"{width}*" if kind == "ptr" else width


def check(cpp_text: str, py_text: str, cpp_rel: str = DEFAULT_CPP,
          py_rel: str = DEFAULT_PY) -> List[Finding]:
    """Cross-check one (host_runtime.cpp, native/__init__.py) pair."""
    findings: List[Finding] = []
    exports, c_version = parse_cpp(cpp_text)
    pb = _PyBindings()
    pb.visit(ast.parse(py_text, filename=py_rel))

    if c_version is None:
        findings.append(Finding(cpp_rel, 1, "ABI004",
                                "rt_abi_version() not found in the C++ "
                                "runtime"))
    elif pb.version is None:
        findings.append(Finding(py_rel, 1, "ABI004",
                                "ABI_VERSION constant not found in the "
                                "binding"))
    elif c_version != pb.version:
        findings.append(Finding(
            py_rel, pb.version_line, "ABI004",
            f"ABI_VERSION={pb.version} but rt_abi_version() returns "
            f"{c_version} — bump both in the same commit"))

    for name in sorted(set(exports) | set(pb.argtypes)):
        if name not in exports:
            line = pb.argtypes[name][0]
            findings.append(Finding(
                py_rel, line, "ABI001",
                f"{name} has argtypes but no extern \"C\" definition in "
                f"{cpp_rel}"))
            continue
        c_ret, c_args = exports[name]
        if name not in pb.argtypes:
            findings.append(Finding(
                py_rel, 1, "ABI001",
                f"extern \"C\" {name} has no argtypes binding — ctypes "
                "would guess int-sized arguments"))
            continue
        line, py_args = pb.argtypes[name]
        if len(c_args) != len(py_args):
            findings.append(Finding(
                py_rel, line, "ABI002",
                f"{name}: {len(py_args)} argtypes vs {len(c_args)} C "
                "parameters"))
        else:
            for i, (ca, pa) in enumerate(zip(c_args, py_args)):
                if pa[0] == "?":
                    findings.append(Finding(
                        py_rel, line, "ABI003",
                        f"{name} arg {i}: unresolvable argtype {pa[1]!r}"))
                elif not _compatible(ca, pa):
                    findings.append(Finding(
                        py_rel, line, "ABI003",
                        f"{name} arg {i}: binding passes {_fmt(pa)} but C "
                        f"expects {_fmt(ca)}"))
        # return type
        got = pb.restype.get(name)
        if c_ret == ("val", "void"):
            if got is not None and got[1] != ("val", "void"):
                findings.append(Finding(
                    py_rel, got[0], "ABI005",
                    f"{name}: restype set to {_fmt(got[1])} but C returns "
                    "void"))
        else:
            if got is None:
                findings.append(Finding(
                    py_rel, line, "ABI005",
                    f"{name}: C returns {_fmt(c_ret)} but restype is "
                    "unset (ctypes truncates to c_int)"))
            elif not _compatible(c_ret, got[1]):
                findings.append(Finding(
                    py_rel, got[0], "ABI005",
                    f"{name}: restype {_fmt(got[1])} but C returns "
                    f"{_fmt(c_ret)}"))
    return findings


def run_paths(cpp_path: str, py_path: str, cpp_rel: str,
              py_rel: str) -> List[Finding]:
    with open(cpp_path, encoding="utf-8") as f:
        cpp_text = f.read()
    with open(py_path, encoding="utf-8") as f:
        py_text = f.read()
    return check(cpp_text, py_text, cpp_rel, py_rel)
