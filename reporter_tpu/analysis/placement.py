"""Transfer-discipline pass (DP rules): host<->device placement.

The pipelined matcher (PR 12) and the device route kernel (PR 15) built
their throughput on one discipline: device values cross back to the
host at exactly two declared points — the drain-lane d2h gather and the
deferred-route resolve at wire time. Any other materialisation
(``np.asarray``/``.item()``/``float()``/``np.array`` on a device array)
is a hidden synchronisation: the submitting thread blocks on the device
queue, the overlap the lanes exist for collapses, and nothing crashes —
the bench just quietly loses its pipelining. Exa.TrkX's acceleration
writeups (PAPERS.md) call the transfer points exactly where pipelined
throughput silently dies; this pass makes them a lint-visible contract.

The pass walks the package call graph from the ``registry.DEVICE_LANES``
entry points (the lane roots are registry-declared because the real
submits go through the ``_lane_stage`` indirection, which structural
pool-root detection cannot see), resolving calls like lockgraph does
(same-class -> same-module -> package-wide-unique, stdlib protocol
names barred) and *stopping* at ``registry.SYNC_POINTS`` — the
whitelisted materialisation sites. Device values are tracked as locals
assigned from kernel-entry calls (the ``KERNEL_CONTRACTS`` entries plus
the ``decode_batch`` facade), closed over functions that return them.

DP001  host materialisation of a device value reachable from a device
       lane outside the SYNC_POINTS whitelist (also: a SYNC_POINTS
       entry naming no existing function — a dangling whitelist is a
       hole, not a contract).
DP002  the same materialisation inside a loop that also dispatches
       device work: a device<->host round trip per iteration, the
       worst version of the bug.
DP003  a declared device-resident path handing a bare numpy array to a
       jit entry (implicit h2d per call; wrap at the boundary with
       ``jnp.asarray``/``device_put``) — also flags a dangling
       DEVICE_LANES entry.

Known approximations (err toward silence, suppressions are the escape
hatch): values are tracked per-function through direct call assignment
only — attribute loads, container round-trips and cross-function
argument flow are not followed; ``bool()``/``int()`` casts are NOT
sinks (the route kernel's convergence check ``bool(converged)`` is a
deliberate, circuit-visible sync).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry
from .core import Finding, SourceFile, dotted, terminal_name
from .jit_hygiene import _Module
from .lockgraph import _Resolver, _callback_name

RULES = {
    "DP001": "host materialisation of a device value outside SYNC_POINTS",
    "DP002": "device<->host round trip inside a dispatching loop",
    "DP003": "numpy array handed to a jit entry on a device-resident path",
}

REGISTRY_REL = "reporter_tpu/analysis/registry.py"

#: contract-key terminal names too generic to treat as producers by
#: bare-name matching (a passed-in ``kernel`` wrapper, a pallas body)
_GENERIC_ENTRIES = frozenset({"kernel", "_forward_kernel"})
#: materialisation sinks, exactly the ISSUE 17 set — bool()/int() are
#: deliberate scalar syncs (convergence checks) and stay legal
_NP_SINKS = frozenset({"asarray", "array"})


def _default_entry_names() -> Set[str]:
    names = {k.split("::")[1] for k in registry.KERNEL_CONTRACTS}
    return (names - _GENERIC_ENTRIES) | {"decode_batch"}


class _Fn:
    """One module- or class-level function (duck-typed for lockgraph's
    _Resolver: key / relpath / cls / local_names)."""

    __slots__ = ("key", "relpath", "cls", "local_names", "calls", "node")

    def __init__(self, key: str, relpath: str, cls: Optional[str],
                 node: ast.AST):
        self.key = key
        self.relpath = relpath
        self.cls = cls
        self.node = node
        self.local_names: Set[str] = set()
        self.calls: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not node:
                self.local_names.add(n.name)
            elif isinstance(n, ast.Call):
                leaf = terminal_name(n.func)
                if leaf is not None:
                    self.calls.add(leaf)
                cb = _callback_name(n)
                if cb is not None:
                    self.calls.add(cb)

    @property
    def spec(self) -> str:
        return f"{self.relpath}::{self.key}"


def _collect(files: Sequence[SourceFile]) -> Dict[str, Dict[str, _Fn]]:
    by_file: Dict[str, Dict[str, _Fn]] = {}
    for sf in files:
        funcs: Dict[str, _Fn] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs[node.name] = _Fn(node.name, sf.relpath, None, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        key = f"{node.name}.{sub.name}"
                        funcs[key] = _Fn(key, sf.relpath, node.name, sub)
        by_file[sf.relpath] = funcs
    return by_file


def _assigned_from(node: ast.AST, names: Set[str]) -> Set[str]:
    """Locals assigned (incl. tuple-unpacked) from a call whose terminal
    name is in ``names``."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        leaf = terminal_name(n.value.func)
        if leaf not in names:
            continue
        for t in n.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                out.update(e.id for e in t.elts
                           if isinstance(e, ast.Name))
    return out


def _np_locals(fn: _Fn, np_roots: Set[str]) -> Set[str]:
    """Locals assigned from any ``np.*`` call — host arrays."""
    out: Set[str] = set()
    for n in ast.walk(fn.node):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        d = dotted(n.value.func)
        if d is None or d.split(".")[0] not in np_roots | {"numpy"}:
            continue
        for t in n.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                out.update(e.id for e in t.elts
                           if isinstance(e, ast.Name))
    return out


def _producers(by_file: Dict[str, Dict[str, _Fn]],
               seed: Set[str]) -> Set[str]:
    """Fixpoint: a function returning a device-tracked local becomes a
    producer under its bare name (``_relax``/``_run`` close over the
    kernel calls they wrap)."""
    producers = set(seed)
    changed = True
    while changed:
        changed = False
        for funcs in by_file.values():
            for fn in funcs.values():
                bare = fn.key.rsplit(".", 1)[-1]
                if bare in producers:
                    continue
                dev = _assigned_from(fn.node, producers)
                if not dev:
                    continue
                for n in ast.walk(fn.node):
                    if isinstance(n, ast.Return) and n.value is not None \
                            and any(isinstance(c, ast.Name)
                                    and c.id in dev
                                    for c in ast.walk(n.value)):
                        producers.add(bare)
                        changed = True
                        break
    return producers


def _first_mention(expr: ast.AST, names: Set[str]) -> Optional[str]:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in names:
            return n.id
    return None


class _LaneScan(ast.NodeVisitor):
    """DP sinks inside one lane-reachable function."""

    def __init__(self, fn: _Fn, np_roots: Set[str], device: Set[str],
                 host: Set[str], producers: Set[str],
                 entry_names: Set[str]):
        self.fn = fn
        self.np_roots = np_roots | {"numpy"}
        self.device = device
        self.host = host
        self.producers = producers
        self.entry_names = entry_names
        self.loops: List[bool] = []  # per enclosing loop: dispatches?
        self.findings: List[Finding] = []

    def _emit(self, node: ast.AST, desc: str, name: str) -> None:
        if any(self.loops):
            self.findings.append(Finding(
                self.fn.relpath, node.lineno, "DP002",
                f"{desc} of device value {name!r} inside a loop that "
                "also dispatches device work — a device<->host round "
                "trip per iteration"))
        else:
            self.findings.append(Finding(
                self.fn.relpath, node.lineno, "DP001",
                f"{desc} of device value {name!r} on a device lane "
                "outside registry.SYNC_POINTS — a hidden sync "
                "serialises the pipeline (route it through a declared "
                "sync point)"))

    def _loop(self, node) -> None:
        dispatches = any(isinstance(n, ast.Call)
                         and terminal_name(n.func) in self.producers
                         for n in ast.walk(node))
        self.loops.append(dispatches)
        self.generic_visit(node)
        self.loops.pop()

    visit_For = _loop
    visit_While = _loop
    visit_AsyncFor = _loop  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        leaf = terminal_name(node.func)
        if isinstance(node.func, ast.Name) and node.func.id == "float" \
                and node.args:
            name = _first_mention(node.args[0], self.device)
            if name is not None:
                self._emit(node, "float() cast", name)
        elif d is not None and d.split(".")[0] in self.np_roots \
                and d.split(".")[-1] in _NP_SINKS and node.args:
            name = None
            for a in node.args:
                name = _first_mention(a, self.device)
                if name is not None:
                    break
            if name is not None:
                self._emit(node, f"{d}()", name)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            name = _first_mention(node.func.value, self.device)
            if name is not None:
                self._emit(node, ".item()", name)
        if leaf in self.entry_names:
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in self.host:
                    self.findings.append(Finding(
                        self.fn.relpath, node.lineno, "DP003",
                        f"numpy array {a.id!r} handed straight to jit "
                        f"entry {leaf}() on a device-resident path — "
                        "an implicit h2d transfer per call; wrap it in "
                        "jnp.asarray/device_put at the boundary"))
        self.generic_visit(node)


def _registry_lines(repo_root: str) -> Dict[str, int]:
    path = os.path.join(repo_root, REGISTRY_REL)
    out: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.setdefault(node.value, node.lineno)
    return out


def run(files: Sequence[SourceFile], repo_root: str,
        lanes: Optional[Sequence[str]] = None,
        sync_points: Optional[Sequence[str]] = None,
        entry_names: Optional[Set[str]] = None,
        full_scope: bool = True) -> List[Finding]:
    """``full_scope=False`` (partial/fixture runs) skips the dangling
    DEVICE_LANES/SYNC_POINTS reverse checks — those judge the registry
    against the whole package."""
    lanes = list(registry.DEVICE_LANES if lanes is None else lanes)
    sync = set(registry.SYNC_POINTS if sync_points is None
               else sync_points)
    entries = _default_entry_names() if entry_names is None \
        else set(entry_names)

    by_file = _collect(files)
    np_roots_by_rel = {sf.relpath: _Module(sf).alias_roots("numpy")
                       for sf in files}
    resolver = _Resolver(by_file)
    producers = _producers(by_file, entries)
    findings: List[Finding] = []
    reg_lines = _registry_lines(repo_root)

    all_specs = {fn.spec for funcs in by_file.values()
                 for fn in funcs.values()}
    if full_scope:
        for spec in sorted(set(lanes) - all_specs):
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(spec, 1), "DP003",
                f"DEVICE_LANES entry {spec} names no module- or class-"
                "level function — a dangling lane root walks nothing"))
        for spec in sorted(sync - all_specs):
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(spec, 1), "DP001",
                f"SYNC_POINTS entry {spec} names no module- or class-"
                "level function — a dangling whitelist entry is a hole"))

    # BFS over the call graph from the lane roots, stopping at the
    # whitelisted sync points
    roots: List[_Fn] = []
    for spec in lanes:
        relpath, key = spec.split("::", 1)
        fn = by_file.get(relpath, {}).get(key)
        if fn is not None:
            roots.append(fn)
    seen: Set[str] = set()
    work = [fn for fn in roots if fn.spec not in sync]
    while work:
        fn = work.pop()
        if fn.spec in seen:
            continue
        seen.add(fn.spec)
        np_roots = np_roots_by_rel.get(fn.relpath, set())
        device = _assigned_from(fn.node, producers)
        host = _np_locals(fn, np_roots)
        scan = _LaneScan(fn, np_roots, device, host, producers, entries)
        scan.visit(fn.node)
        findings.extend(scan.findings)
        for name in sorted(fn.calls):
            callee = resolver.resolve(fn, name)
            if callee is not None and callee.spec not in sync \
                    and callee.spec not in seen:
                work.append(callee)
    return findings
