"""Fallback-parity pass (FB rules): every device path has a proven twin.

PRs 5/9/11/15 each re-invented the same convention by hand: a fast path
(device decode, native wire writer, device route costs, native prep)
is only shippable because a byte-identical fallback sits behind a
circuit breaker and a kill-switch knob, and a parity test proves the
two legs agree. Nothing enforced the convention — a fifth dual path
could ship with a breaker but no knob, or a knob but no parity test,
and the first time anyone noticed would be mid-incident with the
fallback silently diverged.

``registry.FALLBACK_PAIRS`` makes the convention a contract: one entry
per circuit domain, each declaring the fault site that exercises the
fallback, the kill-switch knob that forces it, and the parity test
that proves it. This pass closes the loop in both directions:

FB001  a ``CircuitBreaker("<domain>", ...)`` constructed in the
       package with no FALLBACK_PAIRS entry for its domain — a dual
       path shipping without the full parity kit.  (A breaker that
       guards quarantine/shedding rather than a dual implementation is
       a deliberate exception: suppress with ``# lint: ignore[FB001]``
       and say why.)
FB002  a registry pair missing a leg (fault_site / knob / parity_test),
       naming a fault site or knob the registry doesn't know, or —
       reverse direction — declaring a domain no breaker in the
       package constructs.
FB003  a parity-test reference pointing at a file that doesn't exist
       or a test name the file doesn't contain — a dangling proof is
       no proof.

FB002's reverse direction and FB003's filesystem checks judge the
registry against the whole package and run only under
``full_scope=True`` (skipped by partial path runs, same as the other
contract passes).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Mapping, Optional, Sequence

from . import registry
from .core import Finding, SourceFile, terminal_name

RULES = {
    "FB001": "circuit breaker domain with no FALLBACK_PAIRS entry",
    "FB002": "FALLBACK_PAIRS entry missing or mis-declaring a leg",
    "FB003": "dangling parity-test reference in FALLBACK_PAIRS",
}

REGISTRY_REL = "reporter_tpu/analysis/registry.py"

#: the breaker class's own module — constructions there are the class
#: definition/docstring examples, not real domains
_EXCLUDE_RELS = frozenset({"reporter_tpu/utils/circuit.py", REGISTRY_REL})

#: the three legs every pair must declare (the domain itself is the key)
_LEGS = ("fault_site", "knob", "parity_test")


def _registry_lines(repo_root: str) -> Dict[str, int]:
    """First-occurrence line of each string constant in registry.py —
    lets registry-side findings point at the real entry."""
    path = os.path.join(repo_root, REGISTRY_REL)
    out: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.setdefault(node.value, node.lineno)
    return out


def _breaker_sites(files: Sequence[SourceFile]) -> List[tuple]:
    """(domain, relpath, lineno) for every literal-domain
    ``CircuitBreaker("...")`` construction in view."""
    sites = []
    for sf in files:
        if sf.relpath in _EXCLUDE_RELS:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "CircuitBreaker"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            sites.append((node.args[0].value, sf.relpath, node.lineno))
    return sites


def run(files: Sequence[SourceFile], repo_root: str,
        pairs: Optional[Mapping[str, Mapping[str, str]]] = None,
        test_texts: Optional[Mapping[str, str]] = None,
        full_scope: bool = True) -> List[Finding]:
    """``pairs``/``test_texts`` are injectable for tests: ``test_texts``
    maps a parity-test file's repo-relative path to its text (default:
    read from ``repo_root``)."""
    if pairs is None:
        pairs = registry.FALLBACK_PAIRS
    findings: List[Finding] = []
    reg_lines = _registry_lines(repo_root)
    sites = _breaker_sites(files)

    # FB001: constructed breaker domain without a registry pair
    for domain, relpath, lineno in sites:
        if domain not in pairs:
            findings.append(Finding(
                relpath, lineno, "FB001",
                f"circuit domain {domain!r} has no registry."
                "FALLBACK_PAIRS entry — a dual path needs a declared "
                "fault site, kill-switch knob and parity test (or a "
                "justified suppression if this breaker guards no dual "
                "implementation)"))

    # FB002 forward: each pair must carry all three legs, and the legs
    # must resolve against the registry's own tables
    for domain in sorted(pairs):
        legs = pairs[domain]
        line = reg_lines.get(domain, 1)
        for leg in _LEGS:
            if not legs.get(leg):
                findings.append(Finding(
                    REGISTRY_REL, line, "FB002",
                    f"FALLBACK_PAIRS[{domain!r}] is missing the "
                    f"{leg!r} leg — the pair is not a full parity "
                    "contract without it"))
        fault_site = legs.get("fault_site")
        if fault_site and fault_site not in registry.FAULT_SITES:
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(fault_site, line), "FB002",
                f"FALLBACK_PAIRS[{domain!r}] names fault site "
                f"{fault_site!r} which is not in registry.FAULT_SITES "
                "— the fallback leg cannot be fault-injected"))
        knob = legs.get("knob")
        if knob and knob not in registry.ENV_KNOBS:
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(knob, line), "FB002",
                f"FALLBACK_PAIRS[{domain!r}] names kill switch "
                f"{knob!r} which is not in registry.ENV_KNOBS — an "
                "undocumented knob is not an operable kill switch"))

    if not full_scope:
        return findings

    # FB002 reverse: a registered pair whose domain no breaker in the
    # package constructs — a contract for a path that does not exist
    constructed = {domain for domain, _, _ in sites}
    for domain in sorted(set(pairs) - constructed):
        findings.append(Finding(
            REGISTRY_REL, reg_lines.get(domain, 1), "FB002",
            f"FALLBACK_PAIRS[{domain!r}] matches no CircuitBreaker "
            "construction in the package — dead pair entries hide "
            "real coverage gaps"))

    # FB003: the parity-test reference must point at a real file and a
    # name that file actually contains
    for domain in sorted(pairs):
        ref = pairs[domain].get("parity_test")
        if not ref:
            continue  # already an FB002
        line = reg_lines.get(ref, reg_lines.get(domain, 1))
        relpath, _, name = ref.partition("::")
        if test_texts is not None:
            text = test_texts.get(relpath)
        else:
            try:
                with open(os.path.join(repo_root, relpath),
                          encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                text = None
        if text is None:
            findings.append(Finding(
                REGISTRY_REL, line, "FB003",
                f"FALLBACK_PAIRS[{domain!r}] parity test {ref!r} "
                "points at a file that does not exist"))
            continue
        missing = [part for part in name.split("::")
                   if part and part not in text]
        if not name or missing:
            findings.append(Finding(
                REGISTRY_REL, line, "FB003",
                f"FALLBACK_PAIRS[{domain!r}] parity test {ref!r} "
                f"names {missing[0] if missing else '(nothing)'!r} "
                f"which {relpath} does not define — a dangling proof "
                "is no proof"))
    return findings
