"""JIT hygiene pass (JH rules).

The decode kernels are only correct-and-fast while the code inside their
traced regions stays device-pure: a numpy call on a tracer forces a host
sync (ConcretizationError at best, a silent d2h round trip at worst), a
dtype-less array constructor drifts with the x64 flag and weak-type
promotion, and a Python branch on tensor *values* retraces per value (or
throws under jit). Nothing in the type system marks "this function runs
under trace" — so this pass computes it: every function reachable from a
``jax.jit``/``pjit``/``pallas_call`` entry point through module-level
calls and closure references is a jit region.

JH001  host sync inside a jit region: ``np.*`` calls, ``jax.device_get``,
       ``.block_until_ready()``, ``.item()``/``.tolist()``, and builtin
       ``float()``/``int()``/``bool()`` casts of non-literal values (all
       concretise tracers).
JH002  dtype drift inside a jit region: ``jnp`` array constructors with
       no ``dtype=`` (platform/x64-flag dependent, and Python-scalar
       arrays stay weak-typed, promoting against the declared compute
       dtype — matcher/hmm.py scores everything in f32), plus
       ``.astype(float)``/``.astype(int)`` with Python builtin types.
JH003  data-dependent Python branching inside a jit region: ``if``/
       ``while``/ternary tests referencing a traced parameter's *values*.
       Shape/dtype attribute access (``x.shape``, ``x.ndim``, ...) and
       ``len()``/``isinstance()`` are static under trace and exempt —
       ``trim_time_pad``'s shape branch is the sanctioned pattern.

Known approximations (documented, not bugs): reachability follows names —
a function referenced but never called from a jit region is still
scanned; locals assigned from tracers are not tracked (parameters are).
Both err toward flagging, with suppressions as the escape hatch.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, dotted

RULES = {
    "JH001": "host sync inside a jit-traced region",
    "JH002": "dtype-less constructor / weak-type promotion in a jit region",
    "JH003": "data-dependent Python branch inside a jit-traced region",
}

_SHAPE_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding",
                          "weak_type", "itemsize", "nbytes"})
_STATIC_CALLS = frozenset({"len", "isinstance", "hasattr", "getattr",
                           "type", "range"})
# jnp constructors and the 0-based positional index of their dtype
# parameter (flagged when dtype is passed neither by keyword nor
# positionally, i.e. len(args) <= index)
_CTOR_DTYPE_POS = {
    "array": 1, "asarray": 1, "zeros": 1, "ones": 1, "empty": 1,
    "full": 2, "arange": 3, "linspace": 5, "eye": 3, "identity": 1,
}


class _Module:
    """Per-file symbol info: top-level functions, import aliases."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.name = _module_name(sf.relpath)
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.import_alias: Dict[str, str] = {}        # alias -> module path
        self.import_from: Dict[str, Tuple[str, str]] = {}  # alias -> (mod, sym)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.import_from[a.asname or a.name] = (base, a.name)
        for node in sf.tree.body:  # top level only
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def _resolve_from(self, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        # current package: the module's parent (itself for __init__)
        parts = self.name.split(".")
        if not self.sf.relpath.endswith("__init__.py"):
            parts = parts[:-1]
        parts = parts[:len(parts) - (node.level - 1)] if node.level > 1 \
            else parts
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    def alias_roots(self, *targets: str) -> Set[str]:
        """Local names bound to any of the given module paths."""
        out = set()
        for alias, mod in self.import_alias.items():
            if mod in targets:
                out.add(alias)
        for alias, (mod, sym) in self.import_from.items():
            if f"{mod}.{sym}" in targets:
                out.add(alias)
        return out


def _module_name(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.replace(os.sep, "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return set()


def _jit_like(expr: ast.AST, mod: _Module) -> Optional[Set[str]]:
    """If ``expr`` is a jit-wrapping expression (jax.jit, pjit, a
    functools.partial around one), return its static_argnames; else None."""
    d = dotted(expr)
    jax_names = mod.alias_roots("jax")
    if d is not None:
        head = d.split(".")[0]
        if d.split(".")[-1] in ("jit", "pjit") and (
                head in jax_names or head in ("jax", "pjit")
                or d in ("jit", "pjit")):
            return set()
        # a bare decorator name imported from jax: `from jax import jit`
        tgt = mod.import_from.get(d)
        if tgt is not None and tgt[0].startswith("jax") \
                and tgt[1] in ("jit", "pjit"):
            return set()
    if isinstance(expr, ast.Call):
        d = dotted(expr.func)
        if d is not None and d.split(".")[-1] == "partial" and expr.args:
            inner = _jit_like(expr.args[0], mod)
            if inner is not None:
                return inner | _static_argnames(expr)
        inner = _jit_like(expr.func, mod)
        if inner is not None:  # @jax.jit(...) with options
            return inner | _static_argnames(expr)
    return None


def _first_func_ref(expr: ast.AST) -> Optional[str]:
    """Name the expression refers to: ``f``, ``f.__wrapped__``."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and expr.attr == "__wrapped__" \
            and isinstance(expr.value, ast.Name):
        return expr.value.id
    return None


def _find_entries(mod: _Module) -> Dict[str, Set[str]]:
    """{function name: static_argnames} for this module's jit entry points."""
    entries: Dict[str, Set[str]] = {}
    pallas_roots = mod.alias_roots("jax.experimental.pallas")
    for node in ast.walk(mod.sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                statics = _jit_like(dec, mod)
                if statics is not None:
                    entries.setdefault(node.name, set()).update(statics)
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None:
                continue
            statics = _jit_like(node.func, mod)
            if statics is not None and node.args:
                target = _first_func_ref(node.args[0])
                if target:
                    entries.setdefault(target, set()).update(
                        statics | _static_argnames(node))
            elif d.split(".")[-1] == "pallas_call" and node.args and (
                    d.split(".")[0] in pallas_roots
                    or "pallas" in d):
                target = _first_func_ref(node.args[0])
                if target:
                    entries.setdefault(target, set())
    return entries


def _referenced_names(func: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(func) if isinstance(n, ast.Name)}


def _collect_regions(files: Sequence[SourceFile]
                     ) -> List[Tuple[_Module, ast.AST, Set[str]]]:
    """(module, function node, static_argnames) for every jit region,
    following references across the scanned package."""
    mods = {m.name: m for m in (_Module(sf) for sf in files)}
    work: List[Tuple[str, str, Set[str]]] = []
    for m in mods.values():
        for fname, statics in _find_entries(m).items():
            work.append((m.name, fname, statics))
    seen: Set[Tuple[str, str]] = set()
    regions: List[Tuple[_Module, ast.AST, Set[str]]] = []
    while work:
        mname, fname, statics = work.pop()
        if (mname, fname) in seen:
            continue
        seen.add((mname, fname))
        mod = mods.get(mname)
        if mod is None:
            continue
        func = mod.functions.get(fname)
        if func is None:
            # an entry naming an imported symbol (e.g. jax.jit applied to
            # a function imported from another scanned module)
            tgt = mod.import_from.get(fname)
            if tgt is not None and tgt[0] in mods:
                work.append((tgt[0], tgt[1], statics))
            continue
        regions.append((mod, func, statics))
        for ref in _referenced_names(func):
            if ref in mod.functions:
                work.append((mname, ref, set()))
            elif ref in mod.import_from:
                tmod, tsym = mod.import_from[ref]
                if tmod in mods:
                    work.append((tmod, tsym, set()))
    return regions


class _RegionVisitor(ast.NodeVisitor):
    """Applies JH rules inside one jit region's subtree."""

    def __init__(self, mod: _Module, statics: Set[str]):
        self.mod = mod
        self.statics = statics
        self.findings: List[Finding] = []
        self.np_roots = mod.alias_roots("numpy")
        self.jnp_roots = mod.alias_roots("jax.numpy")
        self.jax_roots = mod.alias_roots("jax") | {"jax"}
        self.tracers: List[Set[str]] = []

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(self.mod.sf.relpath, node.lineno,
                                     rule, message))

    # -- function scope ----------------------------------------------------
    def run(self, func: ast.AST) -> List[Finding]:
        self._visit_func(func)
        return self.findings

    def _params(self, func) -> Set[str]:
        a = func.args
        names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        return {n for n in names
                if n not in self.statics and n not in ("self", "cls")}

    def _visit_func(self, func) -> None:
        self.tracers.append(self._params(func))
        body = func.body if isinstance(func.body, list) else [func.body]
        for stmt in body:
            self.visit(stmt)
        self.tracers.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_func(node)

    # -- JH001 / JH002 -----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        if d is not None:
            root, leaf = d.split(".")[0], d.split(".")[-1]
            if root in self.np_roots or root == "numpy":
                self._emit("JH001", node,
                           f"numpy call {d}() on traced values forces a "
                           "host sync (use jnp, or move it out of the "
                           "jitted region)")
            elif leaf == "device_get" and root in self.jax_roots:
                self._emit("JH001", node,
                           "jax.device_get inside a jitted region is a "
                           "host sync")
            elif (root in self.jnp_roots and leaf in _CTOR_DTYPE_POS
                  and not any(kw.arg == "dtype" for kw in node.keywords)
                  and len(node.args) <= _CTOR_DTYPE_POS[leaf]
                  and node.args):
                self._emit("JH002", node,
                           f"{d}() without dtype= in a jitted region "
                           "(platform/x64-dependent dtype; Python-scalar "
                           "arrays stay weak-typed and promote against "
                           "the declared compute dtype)")
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "block_until_ready":
                self._emit("JH001", node,
                           ".block_until_ready() inside a jitted region "
                           "is a host sync")
            elif attr in ("item", "tolist") and not node.args:
                self._emit("JH001", node,
                           f".{attr}() concretises a tracer (host sync)")
            elif attr == "astype" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in ("float", "int"):
                    self._emit("JH002", node,
                               f".astype({arg.id}) uses a Python builtin "
                               "type (x64-flag-dependent width); name the "
                               "jnp dtype explicitly")
        elif isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") \
                and node.args and not isinstance(node.args[0], ast.Constant):
            if self._mentions_tracer(node.args[0]):
                self._emit("JH001", node,
                           f"builtin {node.func.id}() cast of a traced "
                           "value concretises it (host sync)")
        self.generic_visit(node)

    # -- JH003 -------------------------------------------------------------
    def _mentions_tracer(self, test: ast.AST) -> bool:
        active = set().union(*self.tracers) if self.tracers else set()

        def scan(n: ast.AST) -> bool:
            if isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS:
                return False  # anything under x.shape/.dtype/... is static
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d is not None and d.split(".")[-1] in _STATIC_CALLS:
                    return False
            if isinstance(n, ast.Name) and n.id in active:
                return True
            return any(scan(c) for c in ast.iter_child_nodes(n))

        return scan(test)

    def _check_branch(self, node, test: ast.AST, kind: str) -> None:
        if self._mentions_tracer(test):
            self._emit("JH003", node,
                       f"{kind} on traced values retraces per value (or "
                       "fails under jit); use jnp.where/lax.cond, or "
                       "branch on .shape/.dtype only")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, node.test, "Python if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, node.test, "Python while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node, node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch(node, node.test, "assert")
        self.generic_visit(node)


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    # each function is visited exactly once: _collect_regions de-dups by
    # (module, name) even when several entries reach the same helper
    findings: List[Finding] = []
    for mod, func, statics in _collect_regions(files):
        findings.extend(_RegionVisitor(mod, statics).run(func))
    return findings
