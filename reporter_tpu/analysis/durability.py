"""Durability pass (DUR rules): the atomic-commit discipline, statically.

The crash-consistency story (PR 5) rests on one protocol — write a temp
file, fsync it, ``os.replace`` it over the final name, fsync the parent
directory — and on the exactly-once-ish egress ordering (epoch marker
committed only after the sink ack). Both were conventions; this pass
pins them on the declared durable-module set
(:data:`registry.DURABLE_MODULES`, the modules whose writes land under
durable roots: datastore partitions, state snapshots, tile sinks and
dead-letter spools).

DUR001  bare ``open(path, "w"/"wb"/"a")`` of a non-temp path in a
        durable module: a crash mid-write leaves a torn final file
        (worse: under a deterministic epoch name the marker may say it
        committed). Write through ``utils.fsio.atomic_write_*`` or the
        tmp+replace protocol. A path is "temp" when its expression
        mentions a tmp-ish name or a dot-prefixed constant.
DUR002  ``os.replace(tmp, final)`` with no fsync of the written temp
        content anywhere before it in the function: rename is atomic
        but NOT durable — power loss can surface the new name empty.
        Function-granular by design: ANY earlier fsync satisfies it (a
        per-file dataflow association is beyond a syntactic pass), so a
        multi-artifact commit that fsyncs one temp but not another
        still passes — review owns per-file completeness; the pass owns
        "there is no fsync at all".
DUR003  no directory fsync after the ``os.replace``: the rename itself
        lives in the directory inode and needs the same barrier
        (``fsio.fsync_dir`` / a ``_fsync_dir`` helper).
DUR004  epoch-marker ordering: in the functions annotated in
        :data:`registry.EPOCH_COMMIT_CONTRACTS`, a commit call (e.g.
        ``commit_epoch``) reachable on a path that has NOT passed the
        ack call (``punctuate``) — the marker would declare an epoch
        durable that never reached the sink.

DUR002/003 only judge replaces whose SOURCE is temp-ish: renames of
already-committed files (ingest quarantine) are not commits and stay
out of scope.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from . import registry
from .core import Finding, SourceFile, terminal_name

RULES = {
    "DUR001": "bare write into a durable root (no tmp+replace commit)",
    "DUR002": "os.replace of a temp file never fsync'd before the rename",
    "DUR003": "no directory fsync after an os.replace commit",
    "DUR004": "epoch marker committed before (or without) the sink ack",
}

_FSYNC_NAMES = frozenset({"fsync", "fsync_path", "fsync_file"})
_DIR_FSYNC_NAMES = frozenset({"fsync_dir", "_fsync_dir"})


def _is_tmpish(node: ast.AST) -> bool:
    """Does a path expression look like a temp name? (mentions a name
    containing "tmp", or a dot-prefixed / tmp-suffixed string constant)"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            v = sub.value
            if "tmp" in v.lower() or v.startswith("."):
                return True
    return False


def _open_mode(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None  # default "r"


class _FuncScan:
    """One function's durability-relevant events, in source order."""

    def __init__(self) -> None:
        self.opens: List[Tuple[int, ast.Call]] = []      # write-mode opens
        self.replaces: List[Tuple[int, ast.Call]] = []   # os.replace calls
        self.fsync_lines: List[int] = []
        self.dir_fsync_lines: List[int] = []


def _scan_function(fn: ast.AST) -> _FuncScan:
    out = _FuncScan()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        leaf = terminal_name(node.func)
        if leaf == "open" and isinstance(node.func, ast.Name):
            mode = _open_mode(node)
            if mode is not None and mode.replace("+", "") \
                    .replace("b", "") in ("w", "a", "x"):
                out.opens.append((node.lineno, node))
        elif leaf == "replace" and isinstance(node.func, ast.Attribute) \
                and terminal_name(node.func.value) == "os":
            out.replaces.append((node.lineno, node))
        elif leaf in _FSYNC_NAMES:
            out.fsync_lines.append(node.lineno)
        elif leaf in _DIR_FSYNC_NAMES:
            out.dir_fsync_lines.append(node.lineno)
    return out


# ---- DUR004: commit-after-ack ordering -------------------------------------

def _contains_call(stmt: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Call) and terminal_name(n.func) == name
               for n in ast.walk(stmt))


def _call_positions(node: ast.AST, name: str) -> List[Tuple[int, int]]:
    return [(n.lineno, n.col_offset) for n in ast.walk(node)
            if isinstance(n, ast.Call) and terminal_name(n.func) == name]


def _check_fragment(node: ast.AST, ack: str, commit: str, acked: bool,
                    bad: List[int]) -> bool:
    """Judge one straight-line fragment (a simple statement, or a
    compound statement's header expression): a commit is bad unless the
    ack already ran, or an ack call appears lexically before it in the
    same fragment (evaluation order for non-pathological code)."""
    acks = _call_positions(node, ack)
    for pos in _call_positions(node, commit):
        if not acked and not any(a < pos for a in acks):
            bad.append(pos[0])
    return acked or bool(acks)


def _check_ordering(body: Sequence[ast.stmt], ack: str, commit: str,
                    acked: bool, bad: List[int]) -> bool:
    """Walk a statement list tracking "has the ack definitely run".
    Records line numbers of commit calls reachable while un-acked.
    Compound statements recurse (their bodies own their own judgement);
    only their header expressions are judged at this level. Returns the
    acked state at the end of the list."""
    for stmt in body:
        if isinstance(stmt, ast.If):
            acked = _check_fragment(stmt.test, ack, commit, acked, bad)
            a = _check_ordering(stmt.body, ack, commit, acked, bad)
            b = _check_ordering(stmt.orelse, ack, commit, acked, bad)
            acked = a and b
        elif isinstance(stmt, (ast.For, ast.While)):
            header = stmt.iter if isinstance(stmt, ast.For) else stmt.test
            acked = _check_fragment(header, ack, commit, acked, bad)
            # loop body may run zero times: state does not advance
            _check_ordering(stmt.body, ack, commit, acked, bad)
            _check_ordering(stmt.orelse, ack, commit, acked, bad)
        elif isinstance(stmt, ast.Try):
            # the body may be cut short by the exception: handlers run
            # with the ENTRY state, and only finally advances it
            _check_ordering(stmt.body, ack, commit, acked, bad)
            for h in stmt.handlers:
                _check_ordering(h.body, ack, commit, acked, bad)
            acked = _check_ordering(stmt.finalbody, ack, commit,
                                    acked, bad)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                acked = _check_fragment(item.context_expr, ack, commit,
                                        acked, bad)
            acked = _check_ordering(stmt.body, ack, commit, acked, bad)
        else:
            acked = _check_fragment(stmt, ack, commit, acked, bad)
    return acked


def _iter_functions(tree: ast.AST):
    """(qualname, node) for every function/method, outermost class path
    included."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield prefix + child.name, child
                yield from walk(child, prefix + child.name + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, prefix + child.name + ".")
    yield from walk(tree, "")


def run(files: Sequence[SourceFile], repo_root: str,
        modules: Optional[Sequence[str]] = None,
        contracts: Optional[Dict[str, Tuple[str, str]]] = None
        ) -> List[Finding]:
    modules = tuple(modules if modules is not None
                    else registry.DURABLE_MODULES)
    contracts = dict(registry.EPOCH_COMMIT_CONTRACTS
                     if contracts is None else contracts)
    findings: List[Finding] = []
    for sf in files:
        in_durable = sf.relpath in modules
        for qualname, fn in _iter_functions(sf.tree):
            key = f"{sf.relpath}::{qualname}"
            if key in contracts:
                ack, commit = contracts[key]
                bad: List[int] = []
                acked = _check_ordering(fn.body, ack, commit, False, bad)
                has_commit = any(
                    _contains_call(s, commit) for s in fn.body)
                for line in bad:
                    findings.append(Finding(
                        sf.relpath, line, "DUR004",
                        f"{commit}() reachable before {ack}() in "
                        f"{qualname} — the epoch marker must commit "
                        "only after the sink ack"))
                if not has_commit:
                    findings.append(Finding(
                        sf.relpath, fn.lineno, "DUR004",
                        f"{qualname} is annotated with an epoch-commit "
                        f"contract but never calls {commit}()"))
            if not in_durable:
                continue
            scan = _scan_function(fn)
            for line, call in scan.opens:
                if not call.args:
                    continue
                if _is_tmpish(call.args[0]):
                    continue
                findings.append(Finding(
                    sf.relpath, line, "DUR001",
                    "bare write into a durable root — a crash leaves a "
                    "torn file under its final name; commit via "
                    "utils.fsio.atomic_write_* (tmp + fsync + replace "
                    "+ dir fsync)"))
            for line, call in scan.replaces:
                if not call.args or not _is_tmpish(call.args[0]):
                    continue  # not a tmp-commit rename
                if not any(fl < line for fl in scan.fsync_lines):
                    findings.append(Finding(
                        sf.relpath, line, "DUR002",
                        "os.replace of a temp file with no fsync before "
                        "the rename — power loss can surface the final "
                        "name with empty contents"))
                if not (any(dl > line for dl in scan.dir_fsync_lines)
                        or any(rl > line for rl, _ in scan.replaces
                               if rl != line)):
                    # the dir fsync may follow the LAST replace of a
                    # multi-step commit; only the final one needs it
                    findings.append(Finding(
                        sf.relpath, line, "DUR003",
                        "no directory fsync after the os.replace — the "
                        "rename lives in the directory inode and needs "
                        "the same barrier (fsio.fsync_dir)"))
    return findings
