"""Fault-coverage pass (FP rules): every failpoint is real and proven.

The failpoint layer (PR 5) is only worth its hooks if the site table
stays honest: a ``failpoint("typo.site")`` never fires and silently
runs a faultless chaos scenario; a ``KNOWN_SITES`` entry with no call
site documents a hook that does not exist; and a site no chaos scenario
or fault test ever arms is an untested failure domain — the exact thing
the layer exists to prevent.

FP001  site-name drift: a ``failpoint(site)`` call whose literal site is
       not in ``registry.FAULT_SITES``; a non-literal site argument
       (unauditable); or ``utils/faults.py``'s ``KNOWN_SITES`` /
       the registry disagreeing (they must be identical — the runtime
       warning table and the lint contract are the same list).
FP002  a registered site with NO ``failpoint()`` call site: the hook
       the registry promises was removed (or never landed).
FP003  a registered site exercised by neither a ``tools/chaos.py``
       scenario nor a ``tests/test_faults.py`` case (substring scan of
       both files — specs are strings, so the site name appears
       verbatim wherever it is armed).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry
from .core import Finding, SourceFile, terminal_name

RULES = {
    "FP001": "failpoint site unknown to the registry (or registry/"
             "KNOWN_SITES drift)",
    "FP002": "registered fault site with no failpoint() call site",
    "FP003": "registered fault site exercised by no chaos scenario or "
             "fault test",
}

FAULTS_REL = "reporter_tpu/utils/faults.py"
REGISTRY_REL = "reporter_tpu/analysis/registry.py"
#: where a site must be exercised (relative to the repo root)
EXERCISE_FILES = ("tools/chaos.py", "tests/test_faults.py")


def _call_sites(files: Sequence[SourceFile]
                ) -> Tuple[Dict[str, List[Tuple[str, int]]],
                           List[Tuple[str, int]]]:
    """({site: [(relpath, line)]}, [unresolvable call locations]) over
    every ``failpoint(...)`` call outside utils/faults.py itself."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    opaque: List[Tuple[str, int]] = []
    for sf in files:
        if sf.relpath in (FAULTS_REL, REGISTRY_REL):
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "failpoint"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                sites.setdefault(node.args[0].value, []).append(
                    (sf.relpath, node.lineno))
            else:
                opaque.append((sf.relpath, node.lineno))
    return sites, opaque


def _known_sites_ast(files: Sequence[SourceFile]
                     ) -> Optional[Tuple[Set[str], int]]:
    """(KNOWN_SITES entries, line) parsed from utils/faults.py."""
    for sf in files:
        if sf.relpath != FAULTS_REL:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "KNOWN_SITES":
                entries: Set[str] = set()
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        entries.add(sub.value)
                return entries, node.lineno
    return None


def _registry_lines(repo_root: str) -> Dict[str, int]:
    path = os.path.join(repo_root, REGISTRY_REL)
    out: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.setdefault(node.value, node.lineno)
    return out


def run(files: Sequence[SourceFile], repo_root: str,
        sites: Optional[Dict[str, str]] = None,
        exercise_texts: Optional[Sequence[str]] = None,
        full_scope: bool = True) -> List[Finding]:
    """``full_scope=False`` (partial/fixture runs) checks only FP001 on
    the given files — FP002/FP003 need the whole package in view."""
    sites = dict(registry.FAULT_SITES if sites is None else sites)
    reg_lines = _registry_lines(repo_root)
    findings: List[Finding] = []

    call_sites, opaque = _call_sites(files)
    for name in sorted(call_sites):
        if name not in sites:
            for rel, line in call_sites[name]:
                findings.append(Finding(
                    rel, line, "FP001",
                    f"failpoint site {name!r} is not in "
                    "registry.FAULT_SITES — a typo'd site never fires "
                    "(register it and mirror KNOWN_SITES)"))
    for rel, line in opaque:
        findings.append(Finding(
            rel, line, "FP001",
            "failpoint() with a non-literal site name — chaos coverage "
            "cannot be audited statically; use a string literal"))

    known = _known_sites_ast(files)
    if known is not None:
        entries, line = known
        for name in sorted(entries - set(sites)):
            findings.append(Finding(
                FAULTS_REL, line, "FP001",
                f"KNOWN_SITES entry {name!r} is missing from "
                "registry.FAULT_SITES — the two lists must be "
                "identical"))
        for name in sorted(set(sites) - entries):
            findings.append(Finding(
                FAULTS_REL, line, "FP001",
                f"registry.FAULT_SITES entry {name!r} is missing from "
                "KNOWN_SITES — arming it would warn as unknown at "
                "runtime"))

    if not full_scope:
        return findings

    for name in sorted(sites):
        if name not in call_sites:
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(name, 1), "FP002",
                f"registered fault site {name!r} has no failpoint() "
                "call site — the hook it promises does not exist"))

    if exercise_texts is None:
        exercise_texts = []
        for rel in EXERCISE_FILES:
            try:
                with open(os.path.join(repo_root, rel),
                          encoding="utf-8") as f:
                    exercise_texts.append(f.read())
            except OSError:
                exercise_texts.append("")
    blob = "\n".join(exercise_texts)
    for name in sorted(sites):
        if name not in blob:
            findings.append(Finding(
                REGISTRY_REL, reg_lines.get(name, 1), "FP003",
                f"fault site {name!r} is exercised by no chaos "
                "scenario or tests/test_faults.py case — an untested "
                "failure domain"))

    return findings
