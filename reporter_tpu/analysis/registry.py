"""The cross-layer contract registry: the single source of truth.

PR 5 made correctness depend on hand-maintained cross-layer lists — the
``REPORTER_TPU_*`` env knobs README documents, the metric names /stats
consumers grep for, the ``KNOWN_SITES`` failpoint table chaos scenarios
arm, and the tmp-write -> fsync -> ``os.replace`` commit discipline of
every durable path. None of them were machine-checked, and five knobs
had already drifted out of README by PR 6. This module is the fix: ONE
declarative registry the contract passes (durability, lockgraph,
registry_drift, fault_coverage) verify both sides of — code that uses
an unregistered name fails lint, and a registry entry nothing uses
fails lint too, so the lists can neither rot nor bloat.

Adding a knob / metric / fault site is a three-line change: the code,
this registry, and (for knobs) README's table — and ``tools/lint.py
--contracts-only`` tells you which line you forgot.

Like the rest of the analysis package this imports nothing beyond the
stdlib, so the lint stage needs no accelerator stack.
"""
from __future__ import annotations

from typing import Dict, Tuple

# ---- environment knobs -----------------------------------------------------
# Every REPORTER_TPU_* name any code in reporter_tpu/, tools/ or
# bench.py (or the C++ runtime) reads. Two-sided with the code
# (registry_drift KN001) and with README's knob table (KN002).
ENV_KNOBS: Dict[str, str] = {
    "REPORTER_TPU_PLATFORM": "cpu|accel|auto backend pin",
    "REPORTER_TPU_PROBE_TIMEOUT_S": "accelerator probe timeout",
    "REPORTER_TPU_PROBE_TRIES": "accelerator probe attempts",
    "REPORTER_TPU_PROBE_CACHE": "probe-verdict cache file",
    "REPORTER_TPU_VIRTUAL_DEVICES": "virtual CPU device count",
    "REPORTER_TPU_COMPILE_CACHE": "persistent XLA compile cache dir",
    "REPORTER_TPU_DECODE": "decode backend: scan|assoc|pallas",
    "REPORTER_TPU_DECODE_CHUNK": "traces per decode dispatch",
    "REPORTER_TPU_PIPELINE": "device-lane overlap on/off",
    "REPORTER_TPU_PREP_THREADS": "native prep worker-pool width",
    "REPORTER_TPU_PREP_TIMINGS": "print native prep phase times",
    "REPORTER_TPU_ROUTE_MEMO": "native cross-call route-pair memo size",
    "REPORTER_TPU_ROUTE_DEVICE": "device route-cost kernel on/off",
    "REPORTER_TPU_ROUTE_PRUNE_SIGMA": "candidate prune margin, sigma mult",
    "REPORTER_TPU_ROUTE_HOPS": "device relax sweep cap (0 = auto)",
    "REPORTER_TPU_ROUTE_CACHE_NODES": "numpy route cache: node entries",
    "REPORTER_TPU_ROUTE_CACHE_PAIRS": "numpy route cache: pair entries",
    "REPORTER_TPU_WIRE": "f16|f32 device wire format",
    "REPORTER_TPU_WIRE_NATIVE": "/report wire writer: auto|off",
    "REPORTER_TPU_SERVICE_PROCS": "pre-fork service worker count",
    "REPORTER_TPU_SHARD": "multi-device mesh decode on/off",
    "REPORTER_TPU_DECODE_SHARD": "decode mesh: auto|on|off",
    "REPORTER_TPU_DEVICE_SLICE": "this process's local-device subset",
    "REPORTER_TPU_SEQ_SHARDS": "sequence-parallel time-axis shards",
    "REPORTER_TPU_BUCKETS": "bucket ladder [+ @waste split threshold]",
    "REPORTER_TPU_COORDINATOR": "jax.distributed rendezvous address",
    "REPORTER_TPU_NUM_PROCESSES": "jax.distributed process count",
    "REPORTER_TPU_PROCESS_ID": "jax.distributed process id",
    "REPORTER_TPU_DATASTORE": "histogram-store dir served on /histogram",
    "REPORTER_TPU_DATASTORE_HANDLES": "partition mmap-handle LRU size",
    "REPORTER_TPU_STORE_LEASE_S": "cross-process writer-lease TTL (0 off)",
    "REPORTER_TPU_COMPACT_INTERVAL_S": "background compactor pace (s)",
    "REPORTER_TPU_CITY_BUDGET_MB": "multi-city residency LRU byte budget",
    "REPORTER_TPU_NATIVE": "C++ host runtime: auto|off (prep kill switch)",
    "REPORTER_TPU_NATIVE_LIB": "prebuilt .so override (sanitizers/CI)",
    "REPORTER_TPU_FAULTS": "deterministic failpoint spec",
    "REPORTER_TPU_CIRCUIT_THRESHOLD": "errors that open the breaker",
    "REPORTER_TPU_CIRCUIT_COOLDOWN_S": "breaker cooldown before a probe",
    "REPORTER_TPU_SUBMIT_RETRIES": "submit requeues before dead-letter",
    "REPORTER_TPU_WRITER_ID": "writer tag in epoch tile names",
    "REPORTER_TPU_CHAOS_REQUIRE_NATIVE": "chaos: missing native = fail",
    "REPORTER_TPU_TRACE": "request tracing on/off (spans + export)",
    "REPORTER_TPU_SLO_MS": "per-stage p99 budgets flipping /health",
    "REPORTER_TPU_FLIGHTREC": "flight-recorder dump dir (0 disables)",
    "REPORTER_TPU_HEARTBEAT_S": "worker heartbeat interval (0 off)",
    "REPORTER_TPU_SHADOW_SAMPLE": "shadow-oracle decode sample fraction",
    "REPORTER_TPU_PROFILE_EVENTS": "profiler wide-event ring capacity",
    "REPORTER_TPU_DEADLETTER_MAX_MB": "spool byte cap (oldest shed)",
    "REPORTER_TPU_REPLAY_INTERVAL_S": "dead-letter drain pace (0 off)",
    "REPORTER_TPU_REPLAY_ATTEMPTS": "replays before .quarantine",
    "REPORTER_TPU_INGEST_LEDGER_MAX": "ingest-ledger keys/partition",
    "REPORTER_TPU_LOCKCHECK": "runtime lock witness: 1 arms, raw = A/B leg",
    "REPORTER_TPU_LOCKCHECK_HOLD_MS": "RC002 long-hold threshold (ms)",
    "REPORTER_TPU_RACEFUZZ": "schedule-fuzz spec seed[:prob][@max_us]",
    "REPORTER_TPU_ADMISSION": "SLO-driven admission gate on /report",
    "REPORTER_TPU_QUEUE_MAX": "dispatcher queue bound, traces (0 = off)",
    "REPORTER_TPU_QUEUE_POLICY": "full-queue shed policy: reject|oldest",
    "REPORTER_TPU_INFLIGHT_MAX": "admitted in-flight cap (0 = derived)",
    "REPORTER_TPU_BATCH_LATENCY_MS": "per-batch latency budget (0 = fixed)",
    "REPORTER_TPU_PRESSURE_HOLD_S": "degradation-ladder hysteresis dwell",
    "REPORTER_TPU_BACKPRESSURE": "streaming offer backpressure (0 = off)",
    "REPORTER_TPU_BACKPRESSURE_LATENCY_S": "submit-EWMA slow-down threshold",
    "REPORTER_TPU_FRESHNESS": "freshness tier (overlay/feed/viewport) gate",
    "REPORTER_TPU_FRESHNESS_MB": "recent-delta overlay byte budget (MB)",
    "REPORTER_TPU_FRESHNESS_WAITERS": "/feed long-poll waiter cap (shed past)",
    "REPORTER_TPU_FRESHNESS_POLL_S": "feed store-watch pace (cross-process)",
    "REPORTER_TPU_INCREMENTAL": "incremental matcher path (off disables)",
    "REPORTER_TPU_INCREMENTAL_LAG": "fixed-lag commit bound, kept points",
    "REPORTER_TPU_INCREMENTAL_MB": "carried-state table byte budget (MB)",
    "REPORTER_TPU_SWAP_SAMPLE": "swap shadow capture sampling fraction",
    "REPORTER_TPU_SWAP_AGREEMENT": "swap flip floor: min shadow agreement",
    "REPORTER_TPU_SWAP_WINDOW": "swap capture-ring size (requests)",
    "REPORTER_TPU_SWAP_FORCE": "override: flip below the agreement floor",
}

# ---- metric names ----------------------------------------------------------
# Every name the code passes to the metrics layer (utils.metrics
# count/timer/observe). Entries ending in ``*`` are prefix patterns for
# dynamically-suffixed families (f-string call sites); pattern entries
# are exempt from the dead-entry check (MT002) precisely because their
# call sites are dynamic — exact entries must have a literal somewhere.
METRICS: Dict[str, str] = {
    # matcher
    "matcher.prep": "host prep per chunk (timer)",
    "matcher.decode_dispatch": "jit call + async d2h start (timer)",
    "matcher.decode_wait": "d2h wait (timer)",
    "matcher.assemble": "run walk + column conversion (timer)",
    "matcher.circuit.*": "breaker transitions + degraded-chunk counts",
    "prep.phase.*": "native prep phase split (candidates/select/routes)",
    "route.device.*": "device route kernel: chunks/sources/fallbacks",
    # numpy route cache
    "route.cache.node_hits": "route cache: node-level hits",
    "route.cache.node_misses": "route cache: node-level misses",
    "route.cache.pair_hits": "route cache: pair-level hits",
    "route.cache.pair_misses": "route cache: pair-level misses",
    # service
    "service.requests": "/report requests",
    # native wire writer (service/wire.py)
    "wire.native": "responses emitted by the C-level writer",
    "wire.fallback": "responses served by the Python columnar writer",
    "wire.errors": "native writer faults (degraded to Python, not 500)",
    "wire.circuit.*": "wire-writer breaker transitions/probes",
    # pre-fork supervisor (service/prefork.py)
    "service.procs.spawned": "worker processes forked at startup",
    "service.procs.deaths": "worker exits outside shutdown",
    "service.procs.restarts": "workers restarted into their slot",
    "service.procs.worker_start": "per-worker post-fork service builds",
    "service.requests.histogram": "/histogram requests",
    "service.handle": "/report handling (timer)",
    "service.histogram": "/histogram handling (timer)",
    "service.errors.*": "error responses by status code",
    "dispatch.batches": "micro-batches dispatched",
    "dispatch.traces": "traces dispatched",
    "dispatch.match_many": "batched match call (timer)",
    "dispatch.errors": "dispatch loop errors",
    # load management (ISSUE 15)
    "dispatch.queue.*": "bounded-queue sheds: rejected/evicted/waits",
    "admission.*": "gate verdicts: admitted + shed.{queue,slo,inflight}",
    "pressure.*": "degradation-ladder transitions + rung effects",
    "batch.latency.*": "EWMA flush model: per-trace latency + caps",
    "backpressure.*": "streaming flow control: delays + sheds",
    "slo.malformed": "malformed SLO specs ignored (fail-open, counted)",
    "decode.shadow.suppressed": "shadow chunks skipped by the ladder",
    # streaming
    "egress.ok": "tile egress successes",
    "egress.fail": "tile egress failures",
    "egress.deadletter": "tile bodies spooled to the dead letter",
    "batch.requeued": "failed submits requeued under budget",
    "batch.dropped": "batches dropped after budget exhaustion",
    "batch.deadletter": "trace JSON spooled for replay",
    "state.epoch_skipped": "restores that skipped a committed epoch",
    "state.save.fail": "failed state snapshots (degraded)",
    "state.epoch_commit.fail": "failed epoch-marker commits (degraded)",
    "matcher.assemble.quarantined": "poisoned traces spooled, chunk kept",
    "deadletter.shed": "spool entries shed by the byte cap (oldest)",
    "replay.traces.ok": "dead-letter traces re-submitted successfully",
    "replay.traces.fail": "dead-letter trace replay attempts that failed",
    "replay.tiles.ok": "dead-letter tiles re-egressed successfully",
    "replay.tiles.fail": "dead-letter tile replay attempts that failed",
    "replay.quarantined": "dead-letter entries moved to .quarantine",
    # pipeline
    "pipeline.gather": "backfill stage 1 (timer)",
    "pipeline.match": "backfill stage 2 (timer)",
    "pipeline.report": "backfill stage 3 (timer)",
    # datastore
    "datastore.ingest.parse": "tile CSV parse (timer)",
    "datastore.ingest.bad_rows": "dropped malformed tile rows",
    "datastore.ingest.dir": "directory replay (timer)",
    "datastore.ingest.quarantined": "tiles quarantined mid-ingest",
    "datastore.ingest.files": "tile files replayed",
    "datastore.ingest.deduped": "ledger-deduped appends (exactly-once)",
    "datastore.ingest.ledger_evicted": "ledger keys aged out by the cap",
    "datastore.tee.deadletter": "tee-failed tiles spooled (sink was ok)",
    "datastore.query": "histogram query (timer)",
    "datastore.aggregate": "observation aggregation (timer)",
    "datastore.aggregate.rows": "observation rows aggregated",
    "datastore.store.append": "segment commit (timer)",
    "datastore.store.compact": "compaction pass (timer)",
    "datastore.store.auto_compactions": "pressure-policy compactions",
    "datastore.store.stale_commits": "seq-fence aborts (lease lapsed)",
    "datastore.query.cache.hits": "partition-handle LRU hits",
    "datastore.query.cache.misses": "partition-handle LRU misses",
    "datastore.query.many": "batched multi-segment query sweep (timer)",
    "datastore.query.bbox": "bbox query: resolve + batched sweep (timer)",
    "datastore.query.batched_segments": "segments served via query_many",
    "datastore.lease.*": "writer-lease acquires/renewals/steals/rejections",
    "datastore.compactor.*": "background compaction passes/compactions",
    "datastore.city.*": "city-residency LRU loads/hits/evictions",
    # map lifecycle (ISSUE 20: graph/version.py + cities.swap)
    "swap.flips": "hot swaps that flipped routing to the new map",
    "swap.refusals": "swaps refused (budget pin or shadow agreement)",
    "swap.shadow.*": "dual-version gate: sampled/checks/agree/mismatch",
    "datastore.epoch.*": "map-version epochs: stamped segments, pinned/"
                         "merged queries, feed epoch events",
    "datastore.profile.exports": "route-memo profile artifacts written",
    "datastore.profile.warmed_pairs": "memo pairs pre-warmed at city load",
    # freshness tier (ISSUE 18: datastore/freshness.py + feed.py)
    "overlay.*": "recent-delta overlay: records/deduped/evicted/committed",
    "feed.*": "change feed: events/polls/delivered/shed/timeouts/watch",
    "viewport.*": "materialised viewport summaries: refreshes/queries",
    "service.requests.feed": "/feed long-poll requests",
    # observability
    "flightrec.dumps": "flight-recorder postmortems written",
    # device-level profiler (obs/profiler.py)
    "decode.compile.count": "decode dispatches that paid an XLA compile",
    "decode.compile.recompiles": "same-shape recompiles (storm signal)",
    "decode.compile": "XLA compile seconds per episode (timer)",
    "decode.dispatch.first": "compiling-dispatch wall (timer)",
    "decode.dispatch.steady": "steady-state dispatch wall (timer)",
    "decode.occupancy.*": "per-bucket occupancy ratio histograms",
    "decode.shard.*": "mesh-path decode chunks + rows fanned across it",
    "decode.bucket.split": "chunks split into finer pow2 sub-buckets",
    "decode.shadow.chunks": "chunks shadow-decoded via the numpy oracle",
    "decode.shadow.sampled": "traces shadow-decoded via the numpy oracle",
    "decode.shadow.mismatch": "shadow decodes scoring off the oracle",
    "decode.shadow.mismatch_ratio": "per-chunk mismatch ratio (timer)",
    "decode.shadow.dropped": "shadow chunks shed (sampler backlogged)",
    "decode.shadow.errors": "shadow decode failures (chunk skipped)",
    "profile.chunks": "wide events recorded",
    # incremental matcher (ISSUE 19: matcher/incremental.py)
    "match.incremental.*": "carried-state path: steps/commits/matches/"
                           "state_bytes/evictions/fallbacks/resets/"
                           "shadow checks + the advance/decode timers",
    # runtime concurrency witness (analysis/racecheck.py)
    "racecheck.findings": "witness/audit findings, all RC rules",
    "racecheck.*": "per-rule finding counts (RC001-RC004)",
}

# ---- failpoint sites -------------------------------------------------------
# Mirrors utils/faults.py KNOWN_SITES (fault_coverage FP001 verifies the
# two stay identical) and adds the coverage contract: every site must
# have >=1 failpoint() call site (FP002) and be exercised by a chaos
# scenario or a tests/test_faults.py case (FP003).
FAULT_SITES: Dict[str, str] = {
    "native.prep": "native prep error -> circuit breaker + fallback",
    "decode.dispatch": "device decode error -> numpy-oracle fallback",
    "matcher.assemble": "assembly error -> per-trace scalar + quarantine",
    "matcher.submit": "report submit failure -> bounded requeue",
    "egress.http": "tile sink failure -> dead-letter spool",
    "datastore.commit": "segment commit failure -> caller quarantine",
    "datastore.compact": "crash mid-compaction -> orphan dir, manifest "
                         "untorn; next holder re-compacts",
    "datastore.lease": "lease I/O failure -> mutation refused (spooled)",
    "state.save": "snapshot failure -> degraded (wider replay window)",
    "worker.offer": "crash at an exact stream position",
    "worker.post_egress": "crash between sink ack and epoch marker",
    "wire.native": "native wire-writer fault -> Python writer, same bytes",
    "admission.gate": "gate/sensor failure -> fail OPEN (admit), counted",
    "route.device": "device route fill error -> native re-prep with routes",
    "match.incremental.commit": "crash/error at a fixed-lag commit -> "
                                "carried state dropped, batch-path replay",
    "city.swap": "crash/error in the widest swap window (candidate "
                 "loaded+gated, old still serving) -> old map keeps "
                 "serving; crash recovery proves exactly-once across "
                 "epochs",
}

# ---- durable layout roots --------------------------------------------------
# Modules whose writes land under durable roots (the datastore
# partition layout, the state snapshot + epoch marker, tile-sink
# output and the dead-letter spools). The durability pass (DUR001-003)
# holds every write here to the fsio commit protocol.
DURABLE_MODULES: Tuple[str, ...] = (
    "reporter_tpu/datastore/store.py",
    "reporter_tpu/datastore/ingest.py",
    # the per-city route-memo profile commits into the store root (a
    # torn profile would warm garbage); the .lease file is deliberately
    # NOT here — it is flock-serialised coordination state whose torn
    # body safely parses as "no holder" (datastore/lease.py docstring)
    "reporter_tpu/datastore/profile.py",
    "reporter_tpu/streaming/state.py",
    "reporter_tpu/streaming/anonymiser.py",
    "reporter_tpu/utils/fsio.py",
    # the flight recorder dumps into the dead-letter layout — a torn
    # postmortem after a crash would be worse than none
    "reporter_tpu/obs/flightrec.py",
    # the shared spool layer owns every dead-letter write (torn spool
    # entries replay as truncation), and the drainer moves entries
    # within the spool roots (.quarantine)
    "reporter_tpu/utils/spool.py",
    "reporter_tpu/streaming/drainer.py",
)

# ---- epoch-marker commit ordering (DUR004) ---------------------------------
# "relpath::qualname" -> (ack_call, commit_call): in the annotated
# function, every ``commit_call`` must be reachable only AFTER an
# ``ack_call`` — the exactly-once-ish egress window (a marker committed
# before the sink acked would make restore skip an epoch the sink never
# got).
EPOCH_COMMIT_CONTRACTS: Dict[str, Tuple[str, str]] = {
    "reporter_tpu/streaming/worker.py::StreamWorker._flush_tiles":
        ("punctuate", "commit_epoch"),
}

# ---- kernel contracts (TC rules) -------------------------------------------
# "relpath::function" for every jax.jit / pallas_call entry point the
# jit_hygiene enumerator finds. Two-sided with the code (tensorcontract
# TC002): an entry here with no jit region is dead, a jit entry missing
# here is uncontracted. The abstract shape/dtype signatures themselves
# live in tools/kernel_contracts.json (regenerated by
# ``python -m reporter_tpu.analysis.tensorcontract --write``); entries
# the eval harness cannot drive stand-alone (a passed-in kernel wrapper,
# a pallas kernel body) are covered through their callers and carry no
# JSON cases.
KERNEL_CONTRACTS: Dict[str, str] = {
    "reporter_tpu/ops/route_relax.py::relax_csr":
        "multi-source bounded relaxation -> (S,N) dist/time kernels",
    "reporter_tpu/ops/route_relax.py::pair_costs":
        "route-tensor assembly -> (B,T-1,K,K) costs + max_finite",
    "reporter_tpu/ops/route_relax.py::pair_costs_packed":
        "pair_costs behind two packed h2d blobs (warm dispatch)",
    "reporter_tpu/ops/assoc_viterbi.py::viterbi_assoc_batch":
        "associative-scan decode -> (B,T) paths + (B,) scores",
    "reporter_tpu/ops/pallas_viterbi.py::viterbi_pallas_batch":
        "pallas fused decode -> (B,T) paths + (B,) scores",
    "reporter_tpu/ops/pallas_viterbi.py::_forward_kernel":
        "pallas kernel body (covered via viterbi_pallas_batch; no "
        "stand-alone eval cases)",
    "reporter_tpu/matcher/hmm.py::viterbi_decode_batch":
        "scan decode -> (B,T) paths + (B,) scores (the oracle twin)",
    "reporter_tpu/parallel/sharded.py::kernel":
        "sharded wrapper over a passed-in decode kernel (signature "
        "owned by the wrapped entry; no stand-alone eval cases)",
    "reporter_tpu/parallel/sharded.py::viterbi_assoc_batch":
        "mesh-sharded re-jit of assoc decode (signature owned by "
        "ops/assoc_viterbi.py; needs a Mesh, no stand-alone eval cases)",
    "reporter_tpu/ops/incremental.py::incremental_step_batch":
        "one-point incremental Viterbi advance -> (N,K) scores + bp "
        "+ (N,) restart anchors",
}

# ---- device lanes / host-sync whitelist (DP rules) -------------------------
# DEVICE_LANES are the prep/dispatch/drain thread entry points the
# placement pass walks (the real submits go through the _lane_stage
# indirection, so structural pool-root detection cannot find them).
# SYNC_POINTS are the functions allowed to materialise device arrays on
# the host (np.asarray/.item()/float()): traversal from a lane stops
# there. Everything else reachable from a lane that synchronises is a
# DP001 — the class of bug that silently serialises the pipeline.
DEVICE_LANES: Dict[str, str] = {
    "reporter_tpu/matcher/matcher.py::SegmentMatcher._dispatch_stage":
        "dispatch lane: jit call + async d2h start",
    "reporter_tpu/matcher/matcher.py::SegmentMatcher._drain_stage":
        "drain lane: d2h wait + assembly",
    "reporter_tpu/graph/route_device.py::DeviceRouteKernel.fill_prep":
        "prep-thread route fill (native prepare_batch skip_routes path)",
}

SYNC_POINTS: Dict[str, str] = {
    "reporter_tpu/matcher/matcher.py::SegmentMatcher._drain_stage":
        "THE d2h gather: np.asarray(decoded) under matcher.decode_wait",
    "reporter_tpu/matcher/batchpad.py::PaddedBatch.finalize_wire":
        "deferred route resolve + wire-dtype decision at dispatch time",
    "reporter_tpu/graph/route_device.py::DeferredRoutes.write_back":
        "route-tensor d2h write into the prep dict (idempotent)",
}

# ---- fallback parity pairs (FB rules) --------------------------------------
# Keyed by circuit-breaker domain: every dual path (a device/native fast
# path with a byte-identical host fallback) declares its fault site, its
# kill-switch knob, and the parity test that pins the two paths equal.
# Two-sided with the code (fallback FB001/FB002): a CircuitBreaker
# domain with no pair here is an undeclared dual path, and a pair whose
# legs dangle (unknown site/knob, missing test) is a paper contract.
FALLBACK_PAIRS: Dict[str, Dict[str, str]] = {
    "matcher.circuit": {  # native prep <-> numpy prep
        "fault_site": "native.prep",
        "knob": "REPORTER_TPU_NATIVE",
        "parity_test": "tests/test_report_writer.py::"
                       "test_report_json_native_equals_fallback_bytes",
    },
    "matcher.circuit.decode": {  # device decode <-> numpy oracle
        "fault_site": "decode.dispatch",
        "knob": "REPORTER_TPU_DECODE",
        "parity_test": "tests/test_faults.py::TestDecodeDomain",
    },
    "matcher.circuit.route": {  # device routes <-> host Dijkstra
        "fault_site": "route.device",
        "knob": "REPORTER_TPU_ROUTE_DEVICE",
        "parity_test": "tests/test_route_device.py::"
                       "test_reports_byte_identical",
    },
    "wire.circuit": {  # native wire writer <-> python columnar writer
        "fault_site": "wire.native",
        "knob": "REPORTER_TPU_WIRE_NATIVE",
        "parity_test": "tests/test_report_writer.py::"
                       "test_wire_cross_path_property",
    },
    "matcher.circuit.incremental": {  # carried-state <-> windowed batch
        "fault_site": "match.incremental.commit",
        "knob": "REPORTER_TPU_INCREMENTAL",
        "parity_test": "tests/test_incremental.py::"
                       "test_incremental_matches_batch_noise_profiles",
    },
}

__all__ = ["ENV_KNOBS", "METRICS", "FAULT_SITES", "DURABLE_MODULES",
           "EPOCH_COMMIT_CONTRACTS", "KERNEL_CONTRACTS", "DEVICE_LANES",
           "SYNC_POINTS", "FALLBACK_PAIRS"]
