"""reporter-lint: project-native static analysis for the framework.

Eleven AST-based passes pin the invariants the codebase depends on but
no general-purpose tool can see — four intra-module syntactic passes
(PR 2) and seven cross-layer contract passes against the declarative
registry (:mod:`registry`, PR 6):

  hotpath         HP001-HP003   the columnar host pipeline stays columnar
  jit_hygiene     JH001-JH003   jitted regions stay device-pure
  abi             ABI001-ABI005 the ctypes binding mirrors host_runtime.cpp
  locks           LD001         lock-guarded state is guarded at every write
  lockgraph       LD002-LD003   no lock cycles; no lock held across
                                blocking HTTP/subprocess/native calls
  durability      DUR001-DUR004 tmp+fsync+replace+dir-fsync commits in the
                                durable modules; epoch marker after sink ack
  registry_drift  KN001-KN002   env knobs: code <-> registry <-> README
                  MT001-MT002   metric names: call sites <-> registry
  fault_coverage  FP001-FP003   failpoint sites: registered, hooked,
                                and chaos/test-exercised
  tensorcontract  TC001-TC004   kernel shape/dtype signatures match the
                                committed tools/kernel_contracts.json;
                                every jit entry contracted; no weak-
                                scalar dtype hazards; statics stay
                                non-array
  placement       DP001-DP003   device lanes materialise host values
                                only at registry SYNC_POINTS; no d2h
                                round trips in loops; no numpy handed
                                to jit entries on device paths
  fallback        FB001-FB003   every circuit-broken dual path has a
                                FALLBACK_PAIRS entry with fault site,
                                kill switch and live parity test

Driver: ``python tools/lint.py`` (CI ``lint`` stage; ``--abi-only`` is
the pre-commit ABI guard, ``--contracts-only`` the fast cross-layer
contract guard). Suppress a documented false positive with a
``# lint: ignore[RULE-ID]`` comment on the line (or the line above), or
record it in the committed baseline (``tools/lint_baseline.txt``). See
README "Static analysis" for the rule catalogue and workflow.

This package imports nothing heavy (no jax, no numpy at analysis time
beyond the stdlib ``ast``) so the lint stage starts fast and runs on
hosts with no accelerator stack. The one exception is deliberate and
lazy: tensorcontract's TC001 eval_shape harness imports jax *inside*
``compute_signatures()`` (CPU backend, abstract evaluation only — no
device needed) and records its wall time in ``LAST_EVAL_SECONDS`` so
the lint stage's budget stays visible.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import (abi, durability, fallback, fault_coverage, hotpath,
               jit_hygiene, lockgraph, locks, placement, racecheck,
               registry, registry_drift, tensorcontract)
from .core import (Finding, SourceFile, collect_py_files, compare_baseline,
                   filter_suppressed, load_baseline)

#: per-file code passes, in report order (abi runs separately on its
#: file pair). These are safe on partial runs: a subset of files can
#: only under-report, never false-fire.
CODE_PASSES = (hotpath, jit_hygiene, locks, lockgraph, durability)

#: cross-layer contract passes needing the WHOLE package (plus README /
#: chaos / fault tests) in view — their reverse directions (dead
#: entries, doc drift, coverage) would false-fire on a subset.
CONTRACT_PASSES = (registry_drift, fault_coverage, tensorcontract,
                   placement, fallback)

ALL_RULES: Dict[str, str] = {}
# racecheck's RC rules are runtime findings (the lock witness / guarded
# audit, ISSUE 10), not a static pass — they join the catalogue so
# --list-rules and README document one rule namespace, but no code pass
# emits them.
for _p in (*CODE_PASSES, *CONTRACT_PASSES, abi, racecheck):
    ALL_RULES.update(_p.RULES)


def run_code_passes(files: Sequence[SourceFile],
                    repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for p in CODE_PASSES:
        findings.extend(p.run(files, repo_root))
    return sorted(filter_suppressed(findings, files))


def run_contract_passes(files: Sequence[SourceFile], repo_root: str,
                        full_scope: bool = True) -> List[Finding]:
    """The registry-backed cross-layer passes. ``full_scope`` tells the
    passes whether the whole package is in view (partial runs check only
    the code -> registry direction)."""
    findings: List[Finding] = []
    for p in CONTRACT_PASSES:
        findings.extend(p.run(files, repo_root, full_scope=full_scope))
    return sorted(filter_suppressed(findings, files))


__all__ = ["Finding", "SourceFile", "collect_py_files", "load_baseline",
           "compare_baseline", "filter_suppressed", "run_code_passes",
           "run_contract_passes", "CODE_PASSES", "CONTRACT_PASSES",
           "ALL_RULES", "abi", "hotpath", "jit_hygiene", "locks",
           "lockgraph", "durability", "registry", "registry_drift",
           "fault_coverage", "tensorcontract", "placement", "fallback",
           "racecheck"]
