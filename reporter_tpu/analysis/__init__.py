"""reporter-lint: project-native static analysis for the framework.

Four AST-based passes pin the invariants the codebase depends on but no
general-purpose tool can see:

  hotpath      HP001-HP003  the columnar host pipeline stays columnar
  jit_hygiene  JH001-JH003  jitted regions stay device-pure
  abi          ABI001-ABI005 the ctypes binding mirrors host_runtime.cpp
  locks        LD001        lock-guarded state is guarded at every write

Driver: ``python tools/lint.py`` (CI ``lint`` stage; ``--abi-only`` is
the pre-commit ABI guard). Suppress a documented false positive with a
``# lint: ignore[RULE-ID]`` comment on the line (or the line above), or
record it in the committed baseline (``tools/lint_baseline.txt``). See
README "Static analysis" for the rule catalogue and workflow.

This package imports nothing heavy (no jax, no numpy at analysis time
beyond the stdlib ``ast``) so the lint stage starts fast and runs on
hosts with no accelerator stack.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import abi, hotpath, jit_hygiene, locks
from .core import (Finding, SourceFile, collect_py_files, compare_baseline,
                   filter_suppressed, load_baseline)

#: the code passes, in report order (abi runs separately on its file pair)
CODE_PASSES = (hotpath, jit_hygiene, locks)

ALL_RULES: Dict[str, str] = {}
for _p in (*CODE_PASSES, abi):
    ALL_RULES.update(_p.RULES)


def run_code_passes(files: Sequence[SourceFile],
                    repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for p in CODE_PASSES:
        findings.extend(p.run(files, repo_root))
    return sorted(filter_suppressed(findings, files))


__all__ = ["Finding", "SourceFile", "collect_py_files", "load_baseline",
           "compare_baseline", "filter_suppressed", "run_code_passes",
           "CODE_PASSES", "ALL_RULES", "abi", "hotpath", "jit_hygiene",
           "locks"]
