"""Hot-path purity pass (HP rules).

PR 1 made the host pipeline columnar: trace data crosses every layer as
flat numpy columns, and the measured 62%-of-wall per-trace Python
(BENCH_r05 ``prep`` share) is gone. Nothing enforced that — one innocent
``for p in points`` in a matcher loop would quietly reintroduce it. This
pass pins the invariant on a declared hot-path module set:

HP001  per-element Python loop over trace/point data (a ``for`` statement
       whose iterable is per-point data: ``points``, ``pts``, ``trace``,
       ``probes``, or a ``["trace"]`` subscript). Columnarise instead;
       the single sanctioned per-point pass lives in the declared edge
       functions (``points_to_columns`` and friends).
HP002  dict construction inside a statement loop — the per-trace dict
       builder pattern the columnar pipeline exists to kill. Responses
       serialise straight from run columns to JSON bytes (the columnar
       writers in matcher/matcher.py and service/report.py); the
       remaining edges below are wire-ingestion contracts.
HP003  ``.item()`` anywhere, and ``.tolist()`` inside a loop *body*
       (a ``.tolist()`` in the ``for ... in <iter>`` header runs once and
       is the approved bulk-conversion idiom; per-iteration conversions
       pay fixed numpy overhead per element — the ~4k-tiny-tolist-calls
       regression RunColumns documents).

Edge functions are whitelisted by "relpath::qualname" with a reason; they
are exactly the boundaries where per-element Python is the *contract*
(wire ingestion, JSON response materialisation, the numpy fallback
assembler). Everything else needs a ``# lint: ignore[HP00x]`` with a
comment, or a fix.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, SourceFile

RULES = {
    "HP001": "per-element Python loop over trace data on the hot path",
    "HP002": "dict construction inside a loop on the hot path",
    "HP003": ".item()/.tolist() per-element conversion on the hot path",
}

#: the declared hot-path module set (ISSUE 2): matcher, graph, the
#: columnar batch core, the streaming batcher, the serving-side
#: report/dispatch path that runs once per trace per request, and the
#: datastore's ingest/aggregate kernels (ISSUE 3) — the serving-side
#: analogue of the matcher's batch pipeline, held to the same purity.
HOT_PATH_PREFIXES = (
    "reporter_tpu/matcher/",
    "reporter_tpu/graph/",
    "reporter_tpu/core/tracebatch.py",
    "reporter_tpu/streaming/batcher.py",
    "reporter_tpu/service/report.py",
    "reporter_tpu/service/dispatch.py",
    "reporter_tpu/datastore/ingest.py",
    "reporter_tpu/datastore/aggregate.py",
    # the observability layer rides every hot path above — a per-element
    # loop here would tax every stage at once (ISSUE 7)
    "reporter_tpu/obs/",
)

#: "relpath::qualname" -> why per-element Python is the contract there.
EDGE_FUNCTIONS: Dict[str, str] = {
    # wire ingestion: the single sanctioned pass over point dicts/structs
    "reporter_tpu/core/tracebatch.py::points_to_columns":
        "the one documented place request point dicts are read",
    "reporter_tpu/core/tracebatch.py::TraceBatch.from_requests":
        "request-dict conversion edge (columnarise once at the wire)",
    "reporter_tpu/core/tracebatch.py::PointsView.__getitem__":
        "on-demand point materialisation for dict-shaped consumers",
    "reporter_tpu/core/tracebatch.py::PointsView.__iter__":
        "on-demand point materialisation for dict-shaped consumers",
    "reporter_tpu/streaming/batcher.py::Batch.request_body":
        "HTTP split-deployment JSON body (per-point dicts ARE the wire)",
    "reporter_tpu/streaming/batcher.py::Batch.request_columns":
        "columnarisation edge over Point structs (one pass per flush)",
    # (the old JSON dict builders — _format_runs/_runs_as_lists and the
    # dict-building report() state machine — are gone: the columnar
    # response writer serialises run columns straight to JSON bytes
    # (matcher.render_segments_json / service.report_json), and the
    # emission scan accumulates parallel lists, so none of them need a
    # per-element whitelist anymore)
    # numpy fallback assembler (native assemble_batch replaces it on the
    # hot path; this runs per trace only without the C++ runtime)
    "reporter_tpu/matcher/assemble.py::assemble_segments":
        "numpy fallback assembler + JSON edge (native path bypasses it)",
    "reporter_tpu/matcher/assemble.py::_chain_to_segments":
        "numpy fallback assembler + JSON edge (native path bypasses it)",
    # tile CSV wire ingestion: the one sanctioned per-line pass turning a
    # flushed tile payload into columns (everything downstream is numpy)
    "reporter_tpu/datastore/ingest.py::parse_tile_csv":
        "tile-CSV columnarisation edge (one pass per flushed tile)",
    # graph build/load edges: run at startup or in tooling, not per batch
    "reporter_tpu/graph/osm.py::network_from_osm_xml":
        "OSM import edge (offline graph build)",
    "reporter_tpu/graph/tilestore.py::write_tiles":
        "tile build edge (offline)",
    "reporter_tpu/graph/tilestore.py::merge_tiles":
        "tile load edge (startup)",
    "reporter_tpu/graph/tilestore.py::GraphTileStore":
        "tile load edge (startup)",
    "reporter_tpu/graph/network.py::RoadNetwork.load":
        "graph load edge (startup)",
    "reporter_tpu/graph/network.py::RoadNetwork.save":
        "graph save edge (tooling)",
}

_TRACE_DATA_NAMES = frozenset({"points", "pts", "trace", "probes"})


def _iter_mentions_trace_data(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _TRACE_DATA_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _TRACE_DATA_NAMES:
            return True
        if isinstance(sub, ast.Subscript):
            sl = sub.slice
            if isinstance(sl, ast.Constant) and sl.value == "trace":
                return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []
        self._class_stack: List[str] = []
        self._loop_depth = 0
        self._iter_depth = 0  # inside a For.iter expression

    # -- scope bookkeeping -------------------------------------------------
    def _qualname(self) -> Optional[str]:
        if not self._func_stack and not self._class_stack:
            return None
        return ".".join(self._class_stack + self._func_stack)

    def _whitelisted(self) -> bool:
        parts = self._class_stack + self._func_stack
        # any enclosing scope prefix may be whitelisted (methods of a
        # whitelisted class, helpers nested in a whitelisted function)
        for i in range(1, len(parts) + 1):
            key = f"{self.sf.relpath}::{'.'.join(parts[:i])}"
            if key in EDGE_FUNCTIONS:
                return True
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        if self._whitelisted():
            return
        self.findings.append(Finding(self.sf.relpath, node.lineno, rule,
                                     message))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    # -- rules -------------------------------------------------------------
    def visit_For(self, node: ast.For) -> None:
        if _iter_mentions_trace_data(node.iter):
            self._emit("HP001", node,
                       "per-element loop over trace data "
                       "(columnarise; see analysis/hotpath.py edge list)")
        # the iter expression runs once — .tolist() there is bulk, fine
        self._iter_depth += 1
        self.visit(node.iter)
        self._iter_depth -= 1
        self._loop_depth += 1
        for child in (*node.body, *node.orelse):
            self.visit(child)
        self._loop_depth -= 1
        self.visit(node.target)

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        self._loop_depth += 1
        for child in (*node.body, *node.orelse):
            self.visit(child)
        self._loop_depth -= 1

    def visit_Dict(self, node: ast.Dict) -> None:
        if self._loop_depth and not self._iter_depth and node.keys:
            self._emit("HP002", node,
                       "dict built inside a loop on the hot path "
                       "(build columns and convert in bulk)")
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        if self._loop_depth and not self._iter_depth:
            self._emit("HP002", node,
                       "dict comprehension inside a loop on the hot path")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and not node.args \
                and not node.keywords:
            if func.attr == "item":
                self._emit("HP003", node,
                           ".item() per-element scalar extraction "
                           "(index the array, or convert in bulk)")
            elif func.attr == "tolist" and self._loop_depth \
                    and not self._iter_depth:
                self._emit("HP003", node,
                           ".tolist() inside a loop body (hoist one bulk "
                           "conversion out of the loop)")
        self.generic_visit(node)


def run(files, repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not sf.relpath.startswith(HOT_PATH_PREFIXES):
            continue
        v = _Visitor(sf)
        v.visit(sf.tree)
        findings.extend(v.findings)
    return findings
