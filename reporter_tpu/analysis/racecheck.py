"""Runtime concurrency witness (RC rules): the dynamic half of the lock
discipline the static passes (LD001-LD003) pin syntactically.

Armed through :mod:`reporter_tpu.utils.locks` (``REPORTER_TPU_LOCKCHECK``),
every :class:`~reporter_tpu.utils.locks.TrackedLock` acquire/release and
every Guarded/thread_affine access reports here. The witness maintains a
per-thread held-lock stack and a process-wide *held-before graph* (edge
A -> B when some thread acquired B while holding A, with both
acquisition stacks), and turns what it sees into the same
``path:line: RULE-ID message`` findings the static suite renders —
against the same always-empty baseline.

RC001  runtime lock-order inversion: acquiring B while holding A closed
       a cycle in the held-before graph — some schedule of the involved
       threads deadlocks. Reported once per cycle with both acquisition
       stacks (the edge observed now, and the reverse path's origin).
RC002  long hold: a lock was held longer than the
       ``REPORTER_TPU_LOCKCHECK_HOLD_MS`` threshold — the dynamic
       LD003 analogue (a blocking call under a lock shows up as exactly
       this). Locks constructed ``long_hold_ok=True`` (the native
       once-only build lock) are exempt by design.
RC003  guarded shared state accessed without its owning lock held by
       the accessing thread (:class:`~reporter_tpu.utils.locks.Guarded`).
RC004  thread-affine state touched from a foreign thread
       (:func:`~reporter_tpu.utils.locks.thread_affine`).

Every new finding counts into ``racecheck.*`` metrics and leaves a
flight-recorder postmortem (``racecheck.<rule>``), so a finding in a
long soak is diagnosable after the fact. The witness's own bookkeeping
runs under a *bare* lock and a thread-local re-entrancy guard: the
locks it takes while recording must never feed back into the graph.

Findings are read by ``tests/conftest.py`` (the witness-armed CI leg
fails the pytest session on any finding) and by ``tools/racefuzz.py``
(any finding fails the fuzz run and prints the replay seed).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding

RULES = {
    "RC001": "runtime lock-order inversion (held-before cycle)",
    "RC002": "lock held past the long-hold threshold (dynamic LD003)",
    "RC003": "guarded shared state accessed without its owning lock",
    "RC004": "thread-affine state touched from a foreign thread",
}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: frames belonging to the instrumentation itself, skipped when
#: attributing an event to a call site
_SELF_FILES = (
    os.path.join("reporter_tpu", "utils", "locks.py"),
    os.path.join("reporter_tpu", "analysis", "racecheck.py"),
)

_enabled = False
_hold_ns = 200 * 1_000_000

_tls = threading.local()   # .held: List[_HeldRec]; .busy: bool

#: witness internals run under a BARE lock (a TrackedLock here would
#: re-enter the witness) — lint: the module is instrumentation, not a
#: product-code lock site
_graph_lock = threading.Lock()

# (held_name, acquired_name) -> (site, stack, thread_name)
_edges: Dict[Tuple[str, str], Tuple[str, str, str]] = {}
_adj: Dict[str, Set[str]] = {}
_findings: List[Finding] = []
_reported: Set[Tuple] = set()


class _HeldRec:
    __slots__ = ("lock", "t0_ns", "site", "stack")

    def __init__(self, lock, t0_ns: int, site: Tuple[str, int],
                 stack: str):
        self.lock = lock
        self.t0_ns = t0_ns
        self.site = site
        self.stack = stack


def enable(hold_ms: float) -> None:
    global _enabled, _hold_ns
    _hold_ns = int(max(0.0, hold_ms) * 1e6)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def _call_site(skip_self: bool = True) -> Tuple[Tuple[str, int], str]:
    """((relpath, line), short stack chain) of the nearest caller frame
    outside the instrumentation, repo-relative. The chain keeps up to 4
    repo frames — the "both stack traces" a deadlock report needs
    without dumping whole tracebacks into one message line."""
    frames: List[Tuple[str, int]] = []
    f = sys._getframe(2)
    while f is not None and len(frames) < 4:
        fn = f.f_code.co_filename
        if skip_self and any(fn.endswith(s) for s in _SELF_FILES):
            f = f.f_back
            continue
        if fn.startswith(_REPO_ROOT):
            rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            frames.append((rel, f.f_lineno))
        f = f.f_back
    if not frames:
        return ("<external>", 0), "<external>"
    chain = " <- ".join(f"{p}:{ln}" for p, ln in frames)
    return frames[0], chain


def _record(finding: Finding, dedupe_key: Tuple) -> None:
    """Register one finding (deduped), count it, and leave a
    flight-recorder postmortem. Callers hold the busy guard, so the
    metrics/flightrec locks taken here stay invisible to the graph.

    The side effects re-acquire the metrics-registry and flightrec
    locks — non-reentrant. An RC001 can fire while the reporting thread
    still HOLDS one of them (acquiring the metrics lock under lock A is
    itself the edge that closes a cycle), so each side effect is
    skipped when its lock is on this thread's held stack: the finding
    itself (the contract — render()/conftest gate/fuzz harness) is
    always recorded."""
    with _graph_lock:
        if dedupe_key in _reported:
            return
        _reported.add(dedupe_key)
        _findings.append(finding)
    held_names = {h.lock.name for h in getattr(_tls, "held", ())}
    if "metrics.registry" not in held_names:
        from ..utils import metrics  # lazy: metrics imports locks at import
        metrics.count("racecheck.findings")
        metrics.count(f"racecheck.{finding.rule}")
    if "flightrec" not in held_names:
        from ..obs import flightrec
        flightrec.dump(f"racecheck.{finding.rule}",
                       {"finding": finding.render()})


# ---- lock witness (TrackedLock hooks) --------------------------------------

def note_acquired(lock) -> None:
    if not _enabled or getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        held = getattr(_tls, "held", None)
        if held is None:
            held = _tls.held = []
        site, stack = _call_site()
        rec = _HeldRec(lock, time.perf_counter_ns(), site, stack)
        for h in held:
            if h.lock.name != lock.name:
                _note_edge(h, rec)
        held.append(rec)
    finally:
        _tls.busy = False


def note_released(lock) -> None:
    if not _enabled or getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        held = getattr(_tls, "held", None)
        if not held:
            return
        rec = None
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                rec = held.pop(i)
                break
        if rec is None:
            return  # acquired while busy/pre-arm: nothing to match
        dur_ns = time.perf_counter_ns() - rec.t0_ns
        if dur_ns >= _hold_ns and not lock.long_hold_ok:
            path, line = rec.site
            _record(Finding(
                path, line, "RC002",
                f"lock {lock.name} held {dur_ns / 1e6:.0f} ms "
                f"(threshold {_hold_ns / 1e6:.0f} ms) — acquired here "
                f"({rec.stack}); a blocking call under a lock stalls "
                "every waiter"), ("RC002", lock.name, path, line))
    finally:
        _tls.busy = False


def _note_edge(held: _HeldRec, new: _HeldRec) -> None:
    """Record held.lock -> new.lock; report RC001 when it closes a
    cycle. Runs inside the busy guard."""
    a, b = held.lock.name, new.lock.name
    with _graph_lock:
        first = (a, b) not in _edges
        if first:
            _edges[(a, b)] = (f"{new.site[0]}:{new.site[1]}", new.stack,
                              threading.current_thread().name)
            _adj.setdefault(a, set()).add(b)
        if not first:
            return
        path = _find_path(b, a)
    if path is None:
        return
    cycle = [a] + path  # a -> b -> ... -> a
    key = ("RC001", frozenset(cycle))
    with _graph_lock:
        rev_site, rev_stack, rev_thread = _edges.get(
            (path[-2] if len(path) >= 2 else b, a),
            ("?", "?", "?"))
    order = " -> ".join(cycle)
    p, line = new.site
    _record(Finding(
        p, line, "RC001",
        f"runtime lock-order inversion: {a} -> {b} acquired here "
        f"(thread {threading.current_thread().name}; {new.stack}) "
        f"closes the cycle {order} — the reverse edge into {a} was "
        f"observed at {rev_site} (thread {rev_thread}; {rev_stack}); "
        "opposite-order threads deadlock"), key)


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """A path src -> ... -> dst in the held-before graph (caller holds
    ``_graph_lock``); None when unreachable."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in sorted(_adj.get(node, ())):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


# ---- guarded-state audit (Guarded / thread_affine hooks) -------------------

def note_guard_violation(state_name: str, lock_name: str) -> None:
    if not _enabled or getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        site, stack = _call_site()
        path, line = site
        _record(Finding(
            path, line, "RC003",
            f"guarded state {state_name} accessed without holding its "
            f"lock {lock_name} ({stack}) — every access needs the "
            "lock"), ("RC003", state_name, path, line))
    finally:
        _tls.busy = False


def note_affinity_violation(what: str) -> None:
    if not _enabled or getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        site, stack = _call_site()
        path, line = site
        _record(Finding(
            path, line, "RC004",
            f"thread-affine {what} called from foreign thread "
            f"{threading.current_thread().name} ({stack}) — this "
            "object is single-thread-owned by design"),
            ("RC004", what, path, line))
    finally:
        _tls.busy = False


# ---- reporting -------------------------------------------------------------

def findings() -> List[Finding]:
    with _graph_lock:
        return list(_findings)


def render() -> List[str]:
    """The findings as ``path:line: RC0xx message`` lines (the PR 2
    renderer contract)."""
    return [f.render() for f in sorted(findings())]


def edge_count() -> int:
    """Held-before edges observed so far (tests / harness gauges)."""
    with _graph_lock:
        return len(_edges)


def reset() -> None:
    """Drop the graph, findings and every thread's held state known to
    have been recorded (tests; the per-thread stacks of OTHER threads
    clear lazily as those threads release)."""
    with _graph_lock:
        _edges.clear()
        _adj.clear()
        _findings.clear()
        _reported.clear()
    _tls.held = []


def fork_reset() -> None:
    """Child-side post-fork reset (called by utils.locks' forksafe hook;
    this module stays stdlib-only so it cannot register its own). The
    graph lock is REBOUND, not acquired: a parent thread that held it at
    fork time no longer exists to release it, and every acquisition it
    recorded is a phantom in the child."""
    global _graph_lock
    _graph_lock = threading.Lock()
    _edges.clear()
    _adj.clear()
    _findings.clear()
    _reported.clear()
    _tls.held = []


__all__ = ["RULES", "enable", "disable", "note_acquired", "note_released",
           "note_guard_violation", "note_affinity_violation", "findings",
           "render", "reset", "edge_count", "fork_reset"]
