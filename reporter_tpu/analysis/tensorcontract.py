"""Tensor-contract pass (TC rules): kernel shape/dtype signatures.

PRs 12 and 15 moved decode and route costs onto the device, so the
system's throughput now hangs on invariants no syntactic pass can see: a
silent f32->f64 widening doubles HBM and forks the compile cache, a new
shape axis retraces every bucket, and a dropped output breaks every
caller at dispatch time, not at lint time. This pass closes the loop
with an *abstract-evaluation harness*: ``jax.eval_shape`` drives every
registered jit entry point across representative bucket-ladder shapes —
pure tracing, no device, no FLOPs — and the resulting signatures are
diffed against the committed ``tools/kernel_contracts.json``.

TC001  signature drift: the committed contract and the freshly traced
       signature disagree (dtype widening, a new shape axis, an output
       count change, a static_argnames change), or the contract file
       lags the harness (an entry added/removed without a regen).
TC002  two-sided jit-entry coverage: every entry the jit_hygiene
       enumerator finds must be a ``registry.KERNEL_CONTRACTS`` key and
       vice versa — an uncontracted kernel is invisible to TC001, a
       dead contract is documentation rot.
TC003  weak-typed Python scalar promotion inside jit-reachable code:
       a ``jnp.where`` whose *both* value branches are bare Python
       scalars (or module constants bound to them) has no array operand
       to inherit a dtype from — the result follows the x64 flag, and
       everything downstream promotes with it. One weak branch against
       an array operand is the codebase's sanctioned idiom (the scalar
       adopts the array dtype) and is not flagged.
TC004  ``static_argnames`` naming an array-valued argument: a static
       that is subscripted or carries array attributes inside the
       region is hashed per call (cache storm) or is simply a typo
       naming no parameter at all.

Regen workflow: ``python -m reporter_tpu.analysis.tensorcontract
--write`` rewrites tools/kernel_contracts.json from the live kernels;
the seed-containment test (tests/test_lint.py) pins the committed file
to stay a subset of a fresh regen, so hand edits cannot drift.

Everything except the TC001 harness is stdlib-ast only; jax is imported
lazily inside :func:`compute_signatures` (full-scope runs only), under
``JAX_PLATFORMS=cpu`` by default so the lint stage needs no accelerator.
"""
from __future__ import annotations

import ast
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry
from .core import Finding, SourceFile, dotted
from .jit_hygiene import (_SHAPE_ATTRS, _Module, _collect_regions,
                          _find_entries)

RULES = {
    "TC001": "kernel signature drift against tools/kernel_contracts.json",
    "TC002": "jit entry <-> registry.KERNEL_CONTRACTS coverage gap",
    "TC003": "weak-typed Python scalar promotion in a jit region",
    "TC004": "static_argnames naming an array-valued (or absent) argument",
}

REGISTRY_REL = "reporter_tpu/analysis/registry.py"
CONTRACTS_REL = "tools/kernel_contracts.json"

#: wall seconds of the last eval_shape harness run (None until one runs)
#: — the lint driver prints this so CI can watch the budget.
LAST_EVAL_SECONDS: Optional[float] = None

# ---- the eval_shape harness ------------------------------------------------

#: representative dimensions: candidate fan-out K, node/edge/source
#: counts sized so a full trace stays milliseconds (shapes only — the
#: harness never materialises an array)
_DIMS = {"B": 8, "K": 4, "N": 96, "E": 256, "S": 16}
#: representative rungs of the time-axis bucket ladder
_BUCKETS = (64, 256)

_F32, _I32, _BOOL = "float32", "int32", "bool"


def _decode_cases(d, extra_statics=None):
    """(dist_m, valid, route_m, gc_m, case, sigma, beta) per bucket —
    the shared decode-kernel signature (hmm / assoc / pallas)."""
    B, K = d["B"], d["K"]
    out = []
    for T in _BUCKETS:
        out.append(([((B, T, K), _F32), ((B, T, K), _BOOL),
                     ((B, T - 1, K, K), _F32), ((B, T - 1), _F32),
                     ((B, T), _I32), ((), _F32), ((), _F32)],
                    dict(extra_statics or {})))
    return out


def _incremental_cases(d):
    """(dist_m, valid, route_m, gc_m, case, prev_scores, sigma, beta) —
    one appended kept point for N carried traces (no time axis; N plays
    the batch role B plays in the windowed decode)."""
    N, K = d["B"], d["K"]
    return [([((N, K), _F32), ((N, K), _BOOL), ((N, K, K), _F32),
              ((N,), _F32), ((N,), _I32), ((N, K), _F32),
              ((), _F32), ((), _F32)], {})]


def _relax_cases(d):
    E, S, N = d["E"], d["S"], d["N"]
    return [([((E,), _I32), ((E,), _I32), ((E,), _F32), ((E,), _F32),
              ((S,), _I32), ((), _F32)],
             {"n_nodes": N, "max_iters": 64})]


def _pair_cases(d):
    B, K, E, S, N = d["B"], d["K"], d["E"], d["S"], d["N"]
    out = []
    for T in _BUCKETS:
        out.append(([((B, T, K), _I32), ((B, T, K), _F32), ((B,), _I32),
                     ((B, T - 1), _F32), ((B, T - 1), _F32),
                     ((S, N), _F32), ((S, N), _F32), ((N,), _I32),
                     ((E,), _I32), ((E,), _I32), ((E,), _F32),
                     ((E,), _F32), ((E,), _F32), ((E,), _F32),
                     ((), _F32), ((), _F32)], {}))
    return out


def _packed_cases(d):
    B, K, E, S, N = d["B"], d["K"], d["E"], d["S"], d["N"]
    out = []
    for T in _BUCKETS:
        btk, bt1 = B * T * K, B * (T - 1)
        out.append(([((btk + B + N,), _I32), ((btk + 2 * bt1 + 2,), _F32),
                     ((S, N), _F32), ((S, N), _F32),
                     ((E,), _I32), ((E,), _I32), ((E,), _F32),
                     ((E,), _F32), ((E,), _F32), ((E,), _F32)],
                    {"B": B, "T": T, "K": K, "N": N}))
    return out


#: contract key -> case builder. Keys absent here (the pallas kernel
#: body, the sharded wrappers) are TC002-covered but carry no JSON
#: cases — their signatures are owned by the entries that call them.
_EVAL_SPECS = {
    "reporter_tpu/ops/route_relax.py::relax_csr": _relax_cases,
    "reporter_tpu/ops/route_relax.py::pair_costs": _pair_cases,
    "reporter_tpu/ops/route_relax.py::pair_costs_packed": _packed_cases,
    "reporter_tpu/ops/assoc_viterbi.py::viterbi_assoc_batch":
        _decode_cases,
    "reporter_tpu/ops/pallas_viterbi.py::viterbi_pallas_batch":
        lambda d: _decode_cases(d, {"interpret": True}),
    "reporter_tpu/matcher/hmm.py::viterbi_decode_batch": _decode_cases,
    "reporter_tpu/ops/incremental.py::incremental_step_batch":
        _incremental_cases,
}


def compute_signatures(repo_root: Optional[str] = None) -> dict:
    """Trace every spec'd kernel with jax.eval_shape and return the
    signature table (the exact structure kernel_contracts.json holds).
    CPU-only safe: abstract evaluation allocates nothing and needs no
    device; JAX_PLATFORMS defaults to cpu unless the caller pinned it."""
    global LAST_EVAL_SECONDS
    t0 = time.monotonic()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import functools
    import importlib

    import jax
    import numpy as np

    entries: Dict[str, dict] = {}
    for key in sorted(_EVAL_SPECS):
        relpath, fname = key.split("::")
        modname = relpath[:-3].replace("/", ".")
        fn = getattr(importlib.import_module(modname), fname)
        cases = []
        static_names: Set[str] = set()
        for inputs, statics in _EVAL_SPECS[key](_DIMS):
            static_names |= set(statics)
            args = [jax.ShapeDtypeStruct(shape, np.dtype(dt))
                    for shape, dt in inputs]
            out = jax.eval_shape(functools.partial(fn, **statics), *args)
            leaves = jax.tree_util.tree_leaves(out)
            cases.append({
                "statics": {k: statics[k] for k in sorted(statics)},
                "inputs": [[list(s), d] for s, d in inputs],
                "outputs": [[list(l.shape), str(l.dtype)] for l in leaves],
            })
        entries[key] = {"static_argnames": sorted(static_names),
                        "cases": cases}
    LAST_EVAL_SECONDS = time.monotonic() - t0
    return {"version": 1, "dims": dict(_DIMS),
            "buckets": list(_BUCKETS), "entries": entries}


def _diff_entry(committed: dict, fresh: dict) -> Optional[str]:
    """First human-readable difference between two contract entries."""
    if committed.get("static_argnames") != fresh.get("static_argnames"):
        return (f"static_argnames {committed.get('static_argnames')} != "
                f"traced {fresh.get('static_argnames')}")
    cc, fc = committed.get("cases", []), fresh.get("cases", [])
    if len(cc) != len(fc):
        return f"{len(cc)} contracted case(s) != {len(fc)} traced"
    for i, (c, f) in enumerate(zip(cc, fc)):
        for side in ("statics", "inputs"):
            if c.get(side) != f.get(side):
                return f"case {i} {side}: {c.get(side)} != {f.get(side)}"
        co, fo = c.get("outputs", []), f.get("outputs", [])
        if len(co) != len(fo):
            return (f"case {i}: output count {len(co)} contracted != "
                    f"{len(fo)} traced")
        for j, (a, b) in enumerate(zip(co, fo)):
            if a != b:
                return (f"case {i} output {j}: contracted "
                        f"shape={a[0]} dtype={a[1]}, traced "
                        f"shape={b[0]} dtype={b[1]}")
    return None


# ---- AST side (TC002-004) --------------------------------------------------

def _registry_lines(repo_root: str) -> Dict[str, int]:
    path = os.path.join(repo_root, REGISTRY_REL)
    out: Dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.setdefault(node.value, node.lineno)
    return out


def _def_line(sf: SourceFile, fname: str) -> Optional[int]:
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fname:
            return node.lineno
    return None


def _module_weak_consts(tree: ast.AST) -> Set[str]:
    """Module-level names bound to bare Python numeric literals (the
    ``NEG_INF = -1.0e30`` idiom) — weak-typed wherever they are used."""
    out: Set[str] = set()
    for node in getattr(tree, "body", []):
        if not (isinstance(node, ast.Assign)
                and all(isinstance(t, ast.Name) for t in node.targets)):
            continue
        v = node.value
        if isinstance(v, ast.UnaryOp):
            v = v.operand
        if isinstance(v, ast.Constant) \
                and isinstance(v.value, (int, float)) \
                and not isinstance(v.value, bool):
            out.update(t.id for t in node.targets)
    return out


def _is_weak(node: ast.AST, consts: Set[str]) -> bool:
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    return isinstance(node, ast.Name) and node.id in consts


class _RegionScan(ast.NodeVisitor):
    """TC003 + TC004 array-usage over one jit region's subtree."""

    def __init__(self, mod: _Module, statics: Set[str],
                 consts: Set[str]):
        self.mod = mod
        self.statics = statics
        self.consts = consts
        self.jnp_roots = mod.alias_roots("jax.numpy")
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        if d is not None and d.split(".")[-1] == "where" \
                and d.split(".")[0] in self.jnp_roots \
                and len(node.args) == 3 \
                and _is_weak(node.args[1], self.consts) \
                and _is_weak(node.args[2], self.consts):
            self.findings.append(Finding(
                self.mod.sf.relpath, node.lineno, "TC003",
                "jnp.where with both branches weak Python scalars — no "
                "array operand pins the dtype, so the result follows "
                "the x64 flag and widens everything downstream; wrap "
                "one branch in an explicit jnp dtype"))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.value, ast.Name) \
                and node.value.id in self.statics:
            self.findings.append(Finding(
                self.mod.sf.relpath, node.lineno, "TC004",
                f"static argument {node.value.id!r} is subscripted like "
                "an array — static_argnames hashes it per call (cache "
                "storm) and concretises it at trace time"))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _SHAPE_ATTRS and isinstance(node.value, ast.Name) \
                and node.value.id in self.statics:
            self.findings.append(Finding(
                self.mod.sf.relpath, node.lineno, "TC004",
                f"static argument {node.value.id!r} carries array "
                f"attribute .{node.attr} — it is array-valued; drop it "
                "from static_argnames"))
        self.generic_visit(node)


def run(files: Sequence[SourceFile], repo_root: str,
        contracts: Optional[Dict[str, str]] = None,
        signatures: Optional[dict] = None,
        contracts_path: Optional[str] = None,
        full_scope: bool = True) -> List[Finding]:
    """``full_scope=False`` (partial/fixture runs) checks only the
    code->registry TC002 direction plus TC003/TC004 on the given files —
    the reverse coverage and the TC001 eval harness need the whole
    package (and jax) in view. ``signatures`` injects a pre-computed (or
    deliberately mutated) fresh signature table for tests."""
    contracts = dict(registry.KERNEL_CONTRACTS
                     if contracts is None else contracts)
    findings: List[Finding] = []
    by_rel = {sf.relpath: sf for sf in files}
    reg_lines = _registry_lines(repo_root)

    # -- TC002 forward: every enumerated jit entry is contracted ----------
    enumerated: Dict[str, Set[str]] = {}
    for sf in files:
        mod = _Module(sf)
        for fname, statics in _find_entries(mod).items():
            key = f"{sf.relpath}::{fname}"
            enumerated[key] = statics
            if key not in contracts:
                findings.append(Finding(
                    sf.relpath, _def_line(sf, fname) or 1, "TC002",
                    f"jit entry {key} is not in registry."
                    "KERNEL_CONTRACTS — an uncontracted kernel is "
                    "invisible to the signature diff (TC001)"))

    # -- TC004: statics naming no parameter -------------------------------
    for key, statics in enumerated.items():
        relpath, fname = key.split("::")
        sf = by_rel.get(relpath)
        if sf is None or not statics:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == fname:
                a = node.args
                params = {p.arg for p in (*a.posonlyargs, *a.args,
                                          *a.kwonlyargs)}
                for name in sorted(statics - params):
                    findings.append(Finding(
                        relpath, node.lineno, "TC004",
                        f"static_argnames names {name!r} which is not a "
                        f"parameter of {fname}() — every call re-traces"))
                break

    # -- TC003 / TC004 array-usage over the jit regions --------------------
    consts_by_mod = {sf.relpath: _module_weak_consts(sf.tree)
                     for sf in files}
    for mod, func, statics in _collect_regions(files):
        scan = _RegionScan(mod, statics,
                           consts_by_mod.get(mod.sf.relpath, set()))
        scan.visit(func)
        findings.extend(scan.findings)

    if not full_scope:
        return findings

    # -- TC002 reverse: every contract key has a jit entry -----------------
    for key in sorted(set(contracts) - set(enumerated)):
        findings.append(Finding(
            REGISTRY_REL, reg_lines.get(key, 1), "TC002",
            f"KERNEL_CONTRACTS entry {key} matches no jit entry point "
            "— the kernel it contracts is gone (or renamed)"))

    # -- TC001: the abstract-evaluation diff -------------------------------
    path = contracts_path or os.path.join(repo_root, CONTRACTS_REL)
    try:
        with open(path, encoding="utf-8") as f:
            committed = json.load(f)
    except (OSError, ValueError):
        findings.append(Finding(
            CONTRACTS_REL, 1, "TC001",
            "committed contract file missing or unparseable — run "
            "python -m reporter_tpu.analysis.tensorcontract --write"))
        return findings
    fresh = compute_signatures(repo_root) if signatures is None \
        else signatures
    com_e = committed.get("entries", {})
    fre_e = fresh.get("entries", {})
    for key in sorted(set(com_e) | set(fre_e)):
        relpath, fname = key.split("::")
        sf = by_rel.get(relpath)
        line = (_def_line(sf, fname) if sf is not None else None) or 1
        if key not in fre_e:
            findings.append(Finding(
                relpath if sf is not None else CONTRACTS_REL, line,
                "TC001",
                f"contract entry {key} is no longer traced by the "
                "harness — regenerate tools/kernel_contracts.json"))
            continue
        if key not in com_e:
            findings.append(Finding(
                relpath if sf is not None else CONTRACTS_REL, line,
                "TC001",
                f"kernel {key} is traced by the harness but absent "
                "from the committed contracts — regenerate tools/"
                "kernel_contracts.json"))
            continue
        diff = _diff_entry(com_e[key], fre_e[key])
        if diff is not None:
            findings.append(Finding(
                relpath if sf is not None else CONTRACTS_REL, line,
                "TC001", f"signature drift for {key}: {diff}"))
        # the declared static set is part of the signature
        if key in enumerated and sorted(enumerated[key]) \
                != com_e[key].get("static_argnames", []):
            findings.append(Finding(
                relpath if sf is not None else CONTRACTS_REL, line,
                "TC001",
                f"static_argnames drift for {key}: declared "
                f"{sorted(enumerated[key])}, contracted "
                f"{com_e[key].get('static_argnames', [])}"))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m reporter_tpu.analysis.tensorcontract --write`` —
    regenerate the committed contract file from the live kernels."""
    import argparse
    parser = argparse.ArgumentParser(prog="tensorcontract")
    parser.add_argument("--write", action="store_true",
                        help="rewrite tools/kernel_contracts.json")
    parser.add_argument("--out", default=None,
                        help="override the output path")
    args = parser.parse_args(argv)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sig = compute_signatures(repo_root)
    text = json.dumps(sig, indent=2, sort_keys=True) + "\n"
    if args.write or args.out:
        out = args.out or os.path.join(repo_root, CONTRACTS_REL)
        with open(out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {len(sig['entries'])} contract entr(y/ies) to "
              f"{out} ({LAST_EVAL_SECONDS:.1f}s)")
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
