"""Lightweight metrics: counters, histogram stage timers, optional
device profiling.

The reference's only telemetry is a throughput counter logged every 10k
messages (reference: KeyedFormattingProcessor.java:36-38,
cat_to_kafka.py:59-61) and the per-trace stats block in the /report
response (reporter_service.py:164-177). SURVEY.md §5 lists
tracing/profiling as an absent subsystem to build fresh.

This module is that subsystem, kept deliberately small and lock-cheap:

- ``Registry``: named monotonically-increasing counters and stage
  timers. A timer is a fixed log-bucketed histogram (power-of-2 bounds,
  one numpy bucket increment per observation) plus count/total/max, so
  ``snapshot()`` reports p50/p95/p99 per stage — count/total/max alone
  cannot distinguish "steady 10 ms" from "9 ms with a 2 s tail", and
  the tail is what pages people.
- ``timer(name)``: context manager recording a stage duration. When
  request tracing is armed (``obs.trace``) every timer site doubles as
  a span site — the stage-timer discipline IS the span tree.
- ``device_trace(out_dir)``: context manager wrapping
  ``jax.profiler.trace`` — a real TPU trace viewable in TensorBoard
  or Perfetto — gated so importing this module never imports jax. It
  emits a correlation marker (``jax.profiler.TraceAnnotation`` carrying
  the current trace id) so host spans line up with the XLA profile.

Snapshots report RAW floats: the old 6-decimal rounding collapsed
sub-microsecond timer means to 0.0, which read as "stage never ran".
Rounding is the wire writer's job — ``/stats`` serialises through
:func:`snapshot_rounded` (9 decimals, nanosecond resolution).

All state lives in a process-global default registry (``metrics.default``)
because every consumer in this framework is process-wide (one matcher, one
dispatcher); tests construct private ``Registry`` instances.
"""
from __future__ import annotations

import contextlib
import math
import time
from typing import Dict, Iterator, List, Tuple

import numpy as np

from ..obs import trace as _trace
from . import locks as _locks

#: histogram bucket upper bounds in seconds: powers of two from ~1 µs
#: (2^-20) to 128 s (2^7). Log-spaced buckets keep relative error
#: bounded (<= 2x anywhere) with a bucket index that is one frexp —
#: no search — and 28 bounds cover every stage this framework times
#: (sub-µs flag checks to multi-second cold compiles). One extra
#: overflow bucket catches anything slower.
_BUCKET_EXP_MIN = -20
_BUCKET_EXP_MAX = 7
BUCKET_BOUNDS_S: Tuple[float, ...] = tuple(
    2.0 ** e for e in range(_BUCKET_EXP_MIN, _BUCKET_EXP_MAX + 1))
_N_BUCKETS = len(BUCKET_BOUNDS_S) + 1  # + overflow


def bucket_index(elapsed_s: float) -> int:
    """Histogram bucket for a duration: ``frexp`` exponent, clipped.
    A value in (2^(e-1), 2^e] lands in the bucket bounded by 2^e."""
    if elapsed_s <= 0.0:
        return 0
    # frexp(x) = (m, e) with x = m * 2^e, m in [0.5, 1) — so e is the
    # ceil of log2(x) for non-powers; exact powers land one higher,
    # which still satisfies the le-bound contract (x <= 2^e)
    e = math.frexp(elapsed_s)[1]
    idx = e - _BUCKET_EXP_MIN
    if idx < 0:
        return 0
    if idx >= _N_BUCKETS:
        return _N_BUCKETS - 1
    return idx


class _Timer:
    __slots__ = ("count", "total_s", "max_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.buckets = np.zeros(_N_BUCKETS, dtype=np.int64)

    def add(self, elapsed_s: float) -> None:
        self.count += 1
        self.total_s += elapsed_s
        if elapsed_s > self.max_s:
            self.max_s = elapsed_s
        self.buckets[bucket_index(elapsed_s)] += 1

    def quantile(self, q: float) -> float:
        """Histogram quantile: find the bucket holding the q-th ranked
        observation, interpolate linearly inside it, clamp to the
        observed max (the last bucket is open-ended)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = np.cumsum(self.buckets)
        idx = int(np.searchsorted(cum, target, side="left"))
        lo = BUCKET_BOUNDS_S[idx - 1] if idx > 0 else 0.0
        hi = BUCKET_BOUNDS_S[idx] if idx < len(BUCKET_BOUNDS_S) \
            else self.max_s
        below = int(cum[idx - 1]) if idx > 0 else 0
        in_bucket = int(self.buckets[idx])
        frac = (target - below) / in_bucket if in_bucket else 1.0
        return min(lo + frac * (hi - lo), self.max_s)


class Registry:
    def __init__(self):
        self._lock = _locks.new_lock("metrics.registry")
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, _Timer] = {}

    def count(self, name: str, n: int = 1) -> int:
        """Increment a counter; returns the new value."""
        with self._lock:
            v = self._counters.get(name, 0) + n
            self._counters[name] = v
            return v

    def counter(self, name: str) -> int:
        """One counter's current value (0 when never incremented) — a
        cheap single-name read for telemetry consumers (the worker
        heartbeat, the profiler's wide events) that must not pay a
        whole-registry snapshot copy per read."""
        with self._lock:
            return self._counters.get(name, 0)

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        sp = _trace.span(name)  # no-op unless request tracing is armed
        t0 = time.perf_counter()
        try:
            with sp:
                yield
        finally:
            elapsed = time.perf_counter() - t0
            with self._lock:
                t = self._timers.get(name)
                if t is None:
                    t = self._timers[name] = _Timer()
                t.add(elapsed)

    def observe(self, name: str, elapsed_s: float) -> None:
        """Record a duration measured externally."""
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = _Timer()
            t.add(elapsed_s)

    def snapshot(self) -> dict:
        """{"counters": {...}, "timers": {name: {count, total_s, mean_s,
        max_s, p50_s, p95_s, p99_s}}} — raw floats (see module doc)."""
        with self._lock:
            counters = dict(self._counters)
            timers = {
                name: {
                    "count": t.count,
                    "total_s": t.total_s,
                    "mean_s": t.total_s / t.count if t.count else 0.0,
                    "max_s": t.max_s,
                    "p50_s": t.quantile(0.50),
                    "p95_s": t.quantile(0.95),
                    "p99_s": t.quantile(0.99),
                }
                for name, t in self._timers.items()
            }
        return {"counters": counters, "timers": timers}

    def export_state(self) -> Tuple[Dict[str, int],
                                    Dict[str, Tuple[int, float, float,
                                                    List[int]]]]:
        """One atomic copy for exposition writers: (counters,
        {timer: (count, total_s, max_s, bucket counts)}). Bucket counts
        align with ``BUCKET_BOUNDS_S`` plus one trailing overflow."""
        with self._lock:
            counters = dict(self._counters)
            timers = {name: (t.count, t.total_s, t.max_s,
                             t.buckets.tolist())
                      for name, t in self._timers.items()}
        return counters, timers

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()

    def reset_timers(self) -> None:
        """Clear timers only: bench legs isolate one stage's histogram
        without zeroing cache-hit/egress counters mid-run."""
        with self._lock:
            self._timers.clear()


def snapshot_rounded(registry: "Registry | None" = None,
                     ndigits: int = 9) -> dict:
    """The /stats wire form: :meth:`Registry.snapshot` with timer floats
    rounded for the JSON body. 9 decimals = nanosecond resolution, so
    sub-microsecond stages stay visible (the old 6-decimal rounding
    inside snapshot() flattened them to 0.0)."""
    snap = (registry if registry is not None else default).snapshot()
    snap["timers"] = {
        name: {k: round(v, ndigits) if isinstance(v, float) else v
               for k, v in t.items()}
        for name, t in snap["timers"].items()}
    return snap


#: process-global registry used by the service/worker/pipeline
default = Registry()
count = default.count
counter = default.counter
timer = default.timer
observe = default.observe
snapshot = default.snapshot

# fork safety: a forked worker's /metrics must report ITS work, not a
# copy-on-write snapshot of the parent's (per-process metrics contract,
# README "Serving") — the child's default registry starts empty
from . import forksafe as _forksafe  # noqa: E402

_forksafe.register(default.reset)


@contextlib.contextmanager
def device_trace(out_dir: str) -> Iterator[None]:
    """Capture an XLA/TPU profiler trace into ``out_dir`` (view with
    TensorBoard's profile plugin or Perfetto). A no-op context if jax is
    unavailable. When request tracing is armed, the profiled region is
    wrapped in a ``TraceAnnotation`` naming the current trace id — the
    correlation marker that lines host spans up with the XLA timeline."""
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is baked into this image
        yield
        return
    with _trace.span("device_trace", out_dir=out_dir):
        ctx = _trace.current()
        with jax.profiler.trace(out_dir):
            if ctx is not None:
                with jax.profiler.TraceAnnotation(
                        f"reporter_tpu.trace:{ctx[0]}"):
                    yield
            else:
                yield
