"""Lightweight metrics: counters, stage timers, optional device profiling.

The reference's only telemetry is a throughput counter logged every 10k
messages (reference: KeyedFormattingProcessor.java:36-38,
cat_to_kafka.py:59-61) and the per-trace stats block in the /report
response (reporter_service.py:164-177). SURVEY.md §5 lists
tracing/profiling as an absent subsystem to build fresh.

This module is that subsystem, kept deliberately small and lock-cheap:

- ``Registry``: named monotonically-increasing counters and accumulating
  timers (count / total seconds / max seconds), snapshot-able as a dict
  for logs or a /stats endpoint.
- ``timer(name)``: context manager recording a stage duration.
- ``device_trace(out_dir)``: context manager wrapping
  ``jax.profiler.trace`` — a real TPU trace viewable in TensorBoard
  or Perfetto — gated so importing this module never imports jax.

All state lives in a process-global default registry (``metrics.default``)
because every consumer in this framework is process-wide (one matcher, one
dispatcher); tests construct private ``Registry`` instances.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator


class _Timer:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def add(self, elapsed_s: float) -> None:
        self.count += 1
        self.total_s += elapsed_s
        if elapsed_s > self.max_s:
            self.max_s = elapsed_s


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, _Timer] = {}

    def count(self, name: str, n: int = 1) -> int:
        """Increment a counter; returns the new value."""
        with self._lock:
            v = self._counters.get(name, 0) + n
            self._counters[name] = v
            return v

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            with self._lock:
                t = self._timers.get(name)
                if t is None:
                    t = self._timers[name] = _Timer()
                t.add(elapsed)

    def observe(self, name: str, elapsed_s: float) -> None:
        """Record a duration measured externally."""
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = _Timer()
            t.add(elapsed_s)

    def snapshot(self) -> dict:
        """{"counters": {...}, "timers": {name: {count,total_s,mean_s,max_s}}}"""
        with self._lock:
            counters = dict(self._counters)
            timers = {
                name: {
                    "count": t.count,
                    "total_s": round(t.total_s, 6),
                    "mean_s": round(t.total_s / t.count, 6) if t.count else 0.0,
                    "max_s": round(t.max_s, 6),
                }
                for name, t in self._timers.items()
            }
        return {"counters": counters, "timers": timers}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


#: process-global registry used by the service/worker/pipeline
default = Registry()
count = default.count
timer = default.timer
observe = default.observe
snapshot = default.snapshot


@contextlib.contextmanager
def device_trace(out_dir: str) -> Iterator[None]:
    """Capture an XLA/TPU profiler trace into ``out_dir`` (view with
    TensorBoard's profile plugin or Perfetto). A no-op context if jax is
    unavailable."""
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is baked into this image
        yield
        return
    with jax.profiler.trace(out_dir):
        yield
