"""Instrumented locking: the runtime concurrency-analysis substrate.

PR 2 (LD001) and PR 6 (LD002/LD003) check lock discipline *statically*;
they are blind to locks reached through ``executor.submit`` callbacks,
queue handoffs and the native layer, and they can only prove ordering
the AST shows. This module is the dynamic half (ISSUE 10): every package
lock is a :class:`TrackedLock`, and when the witness is armed
(``REPORTER_TPU_LOCKCHECK=1``) each acquire/release feeds the runtime
held-before graph in :mod:`reporter_tpu.analysis.racecheck`, which
reports lock-order inversions (RC001, potential deadlock) and
long-holds (RC002, dynamic LD003) with the acquisition stacks.

Three cooperating pieces:

- :class:`TrackedLock` — a named ``threading.Lock`` wrapper. Disarmed
  cost is one module-flag load per acquire and per release (pinned by
  ``tools/racefuzz.py --overhead``, the serialized 512-trace A/B).
  ``REPORTER_TPU_LOCKCHECK=raw`` makes :func:`new_lock` hand out bare
  ``threading.Lock`` objects instead — the A leg of that A/B; the
  witness cannot arm in raw mode.
- :class:`Guarded` / :func:`thread_affine` — the shared-state audit.
  ``Guarded(obj, lock, name)`` proxies a mutable object and, when
  armed, asserts the owning :class:`TrackedLock` is held by the calling
  thread on every access (RC003). ``@thread_affine`` marks methods of
  single-thread-owned objects (the dispatcher's drain loop, the
  anonymiser's tile map): the first armed call binds the instance to
  that thread, any other thread's call is RC004.
- :func:`fuzz_point` — the schedule-perturbation layer
  (``REPORTER_TPU_RACEFUZZ=seed[:prob][@max_us]``). Armed, each hook
  (every lock acquire, the dispatcher's queue put/get) draws from a
  per-site seeded RNG (``crc32(site) ^ seed`` — replayable bit-identically
  by seed, like :mod:`.faults`) and sleeps up to ``max_us`` to shake out
  interleavings the scheduler would rarely pick. ``tools/racefuzz.py``
  drives scenarios under N seeds and prints the replay seed on a finding.
"""
from __future__ import annotations

import logging
import os
import random
import sys
import threading
import time
import weakref
import zlib
from typing import Optional

logger = logging.getLogger("reporter_tpu.locks")

ENV_VAR = "REPORTER_TPU_LOCKCHECK"          # 1/on = witness armed; raw = A/B leg
ENV_HOLD_MS = "REPORTER_TPU_LOCKCHECK_HOLD_MS"
ENV_FUZZ = "REPORTER_TPU_RACEFUZZ"          # seed[:prob][@max_us]

#: default RC002 long-hold threshold: generous enough that a loaded CI
#: box holding the metrics lock through a GC pause stays silent, small
#: enough that a lock held across an HTTP round trip or a subprocess
#: does not (the dynamic LD003 analogue)
DEFAULT_HOLD_MS = 200.0

_ENABLED = False      # the one flag every disarmed lock site loads
_RAW = False          # new_lock() hands out bare threading.Lock
_FUZZ: Optional["_FuzzSpec"] = None
_witness = None       # reporter_tpu.analysis.racecheck, set by arm()
#: every live TrackedLock, for the post-fork sweep (WeakSet: a lock's
#: lifetime is its owner's — the sweep must not extend it). Mutated
#: only from __init__ under the GIL; iterated single-threaded in the
#: child's fork hook.
_instances: "weakref.WeakSet[TrackedLock]" = weakref.WeakSet()


class TrackedLock:
    """A named lock the runtime witness can observe. Same contract as
    ``threading.Lock`` (non-reentrant; ``with`` support; ``locked()``)
    plus a stable ``name`` — the node identity in the held-before graph
    (instances sharing a name share a node; same-name edges are skipped,
    so per-instance locks like the circuit breakers' do not self-cycle).

    ``long_hold_ok`` exempts a documented long holder (the native
    once-only build lock: subprocess make + ABI handshake under it is
    the design) from RC002.
    """

    __slots__ = ("_lock", "name", "long_hold_ok", "_owner", "__weakref__")

    def __init__(self, name: str, long_hold_ok: bool = False):
        self._lock = threading.Lock()
        self.name = name
        self.long_hold_ok = long_hold_ok
        self._owner = 0  # acquiring thread id, maintained only when armed
        _instances.add(self)  # fork-safety sweep (forksafe reset hook)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _FUZZ is not None:
            _FUZZ.maybe_yield("lock." + self.name)
        got = self._lock.acquire(blocking, timeout)
        if got and _ENABLED:
            self._owner = threading.get_ident()
            _witness.note_acquired(self)
        return got

    def release(self) -> None:
        if _ENABLED:
            # clear ownership, DROP the lock, then tell the witness:
            # note_released can emit an RC002 finding whose recording
            # acquires the metrics/flightrec locks — if THIS lock is one
            # of those, reporting before releasing would self-deadlock
            # on the non-reentrant underlying lock. The duration skew
            # from measuring after the release is nanoseconds.
            self._owner = 0
            self._lock.release()
            _witness.note_released(self)
        else:
            self._lock.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_me(self) -> bool:
        """Best-effort (armed-mode) check: is this lock held by the
        calling thread? Owner tracking starts at arming, so a lock
        acquired pre-arm reads as not-held — arm before driving."""
        return self._lock.locked() \
            and self._owner == threading.get_ident()


def new_lock(name: str, long_hold_ok: bool = False):
    """The package lock factory: a :class:`TrackedLock`, or a bare
    ``threading.Lock`` under ``REPORTER_TPU_LOCKCHECK=raw`` (the A/B
    baseline leg — zero wrapper overhead, zero observability)."""
    if _RAW:
        return threading.Lock()
    return TrackedLock(name, long_hold_ok=long_hold_ok)


# ---- guarded shared state --------------------------------------------------

class Guarded:
    """Audit proxy around a shared mutable (dict/deque/...): when the
    witness is armed, every access asserts the owning lock is held by
    the calling thread (RC003) — a silent race becomes a named finding.
    Disarmed, each access costs one flag check plus the forward."""

    __slots__ = ("_gd_obj", "_gd_lock", "_gd_name")

    def __init__(self, obj, lock, name: str):
        object.__setattr__(self, "_gd_obj", obj)
        object.__setattr__(self, "_gd_lock", lock)
        object.__setattr__(self, "_gd_name", name)

    def _gd_check(self) -> None:
        lock = self._gd_lock
        if not (isinstance(lock, TrackedLock) and lock.held_by_me()):
            _witness.note_guard_violation(self._gd_name,
                                          getattr(lock, "name", "?"))

    def unwrap(self):
        """The raw object (tests / unguarded bulk handoff)."""
        return self._gd_obj

    def __getattr__(self, attr):
        if _ENABLED:
            self._gd_check()
        return getattr(self._gd_obj, attr)

    def __getitem__(self, key):
        if _ENABLED:
            self._gd_check()
        return self._gd_obj[key]

    def __setitem__(self, key, value):
        if _ENABLED:
            self._gd_check()
        self._gd_obj[key] = value

    def __delitem__(self, key):
        if _ENABLED:
            self._gd_check()
        del self._gd_obj[key]

    def __contains__(self, key):
        if _ENABLED:
            self._gd_check()
        return key in self._gd_obj

    def __iter__(self):
        if _ENABLED:
            self._gd_check()
        return iter(self._gd_obj)

    def __len__(self):
        if _ENABLED:
            self._gd_check()
        return len(self._gd_obj)

    def __bool__(self):
        if _ENABLED:
            self._gd_check()
        return bool(self._gd_obj)


_AFFINE_ATTR = "_thread_affinity_tid"


def thread_affine(method):
    """Mark a method of a single-thread-owned object: the first armed
    call binds the INSTANCE to its thread; a call from any other thread
    is an RC004 finding. All ``@thread_affine`` methods of one instance
    share the binding (one owner thread per object). Disarmed cost is
    one flag check per call; :func:`reset_affinity` (tests) unbinds."""
    import functools

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        if _ENABLED:
            tid = threading.get_ident()
            bound = getattr(self, _AFFINE_ATTR, None)
            if bound is None:
                try:
                    setattr(self, _AFFINE_ATTR, tid)
                except AttributeError:  # __slots__ class: cannot bind
                    pass
            elif bound != tid:
                _witness.note_affinity_violation(
                    f"{type(self).__name__}.{method.__name__}")
        return method(self, *args, **kwargs)

    return wrapper


def reset_affinity(obj) -> None:
    """Drop an instance's thread binding (tests that legitimately hand
    an object to a fresh thread)."""
    try:
        delattr(obj, _AFFINE_ATTR)
    except AttributeError:
        pass


# ---- arming ----------------------------------------------------------------

def armed() -> bool:
    return _ENABLED


def arm(hold_ms: Optional[float] = None) -> None:
    """Arm the witness + audit. ``hold_ms`` overrides the RC002
    long-hold threshold (default ``REPORTER_TPU_LOCKCHECK_HOLD_MS``)."""
    global _ENABLED, _witness
    if _RAW:
        raise RuntimeError(
            f"{ENV_VAR}=raw hands out bare locks; the witness cannot "
            "arm in this process")
    from ..analysis import racecheck
    if hold_ms is None:
        hold_ms = _env_float(ENV_HOLD_MS, DEFAULT_HOLD_MS)
    racecheck.enable(hold_ms)
    _witness = racecheck
    _ENABLED = True


def disarm() -> None:
    global _ENABLED
    _ENABLED = False


# ---- schedule perturbation -------------------------------------------------

class _FuzzSpec:
    """Seeded micro-yield injector. Per-site RNG seeded by
    ``crc32(site) ^ seed`` so the decision/duration SEQUENCE at each
    site replays bit-identically under the same seed (which thread gets
    which draw still depends on the schedule — that is the point)."""

    __slots__ = ("seed", "prob", "max_us", "yields", "_rngs", "_meta")

    def __init__(self, seed: int, prob: float = 0.25,
                 max_us: float = 200.0):
        if not 0.0 < prob <= 1.0:
            raise ValueError(f"fuzz prob {prob} out of (0,1]")
        if max_us <= 0:
            raise ValueError(f"fuzz max_us {max_us} must be positive")
        self.seed = seed
        self.prob = prob
        self.max_us = max_us
        self.yields = 0
        self._rngs = {}
        # a bare lock, deliberately: the fuzzer's own serialisation must
        # not feed the witness or re-enter itself
        self._meta = threading.Lock()

    def maybe_yield(self, site: str) -> None:
        # one draw sequence per site, serialised so replays by seed stay
        # deterministic per site
        with self._meta:
            rng = self._rngs.get(site)
            if rng is None:
                rng = self._rngs[site] = random.Random(
                    zlib.crc32(site.encode("utf-8")) ^ self.seed)
            if rng.random() >= self.prob:
                return
            dur = rng.random() * self.max_us / 1e6
            self.yields += 1
        time.sleep(dur)


def parse_fuzz_spec(spec: str) -> _FuzzSpec:
    """``seed[:prob][@max_us]`` — e.g. ``7``, ``7:0.5``, ``7:0.5@400``.
    Raises ValueError on a malformed spec (a typo'd fuzz run must not
    silently run unperturbed)."""
    body = spec.strip()
    max_us = 200.0
    if "@" in body:
        body, us = body.split("@", 1)
        max_us = float(us)
    prob = 0.25
    if ":" in body:
        body, p = body.split(":", 1)
        prob = float(p)
    return _FuzzSpec(int(body), prob=prob, max_us=max_us)


def configure_fuzz(spec: Optional[str]) -> None:
    """(Re)arm the perturbation layer from a spec string; None/"" off."""
    global _FUZZ
    _FUZZ = parse_fuzz_spec(spec) if spec else None
    if _FUZZ is not None:
        logger.warning("schedule perturbation ARMED: seed=%d prob=%g "
                       "max_us=%g", _FUZZ.seed, _FUZZ.prob, _FUZZ.max_us)


def fuzz_point(site: str) -> None:
    """A perturbation hook at a schedule-sensitive site (queue put/get;
    lock acquires hook internally). One flag check when disarmed."""
    f = _FUZZ
    if f is not None:
        f.maybe_yield(site)


def fuzz_yields() -> int:
    """Yields injected so far (0 when disarmed) — the fuzz harness's
    sanity gauge that perturbation actually happened."""
    f = _FUZZ
    return f.yields if f is not None else 0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.error("ignoring malformed %s=%r", name, raw)
        return default


# ---- fork safety -----------------------------------------------------------

def _fork_reset() -> None:
    """Post-fork child-side sweep (utils.forksafe): a lock some OTHER
    parent thread held at fork time is locked FOREVER in the child — no
    thread exists to release it — so its inner ``threading.Lock`` is
    replaced with a fresh one. The surviving thread's own holds are kept
    when ownership is known (armed mode maintains ``_owner``); disarmed,
    any locked lock is presumed orphaned — the pre-fork serving mode
    forks from a quiet parent, and a fork taken INSIDE a package lock
    would be the bug this sweep exists to surface. Runs FIRST among the
    forksafe hooks (this module registers at import, before every
    consumer of new_lock), so later hooks can safely take the locks
    guarding the state they reset."""
    me = threading.get_ident()
    for lk in list(_instances):
        try:
            if lk._lock.locked() and lk._owner != me:
                lk._lock = threading.Lock()
                lk._owner = 0
        except Exception:  # a dying referent mid-sweep must not poison it
            pass
    # the armed witness's held-before graph records parent acquisitions
    # that will never release in the child
    rc = sys.modules.get("reporter_tpu.analysis.racecheck")
    if rc is not None:
        try:
            rc.fork_reset()
        except Exception:
            pass


from . import forksafe as _forksafe  # noqa: E402  (import registers nothing)

_forksafe.register(_fork_reset)


# arm from the environment at import: the racecheck CI stage and the
# fuzz harness arm subprocesses by env. Malformed values must not brick
# every import site — log loudly and stay disarmed.
_env_val = os.environ.get(ENV_VAR, "").strip().lower()
if _env_val == "raw":
    _RAW = True
elif _env_val and _env_val not in ("0", "off", "false"):
    arm()
_env_fuzz = os.environ.get(ENV_FUZZ)
if _env_fuzz:
    try:
        configure_fuzz(_env_fuzz)
    except ValueError as _e:  # pragma: no cover - env typo path
        logger.error("ignoring malformed %s=%r: %s", ENV_FUZZ, _env_fuzz, _e)

__all__ = ["TrackedLock", "Guarded", "new_lock", "thread_affine",
           "reset_affinity", "arm", "disarm", "armed", "configure_fuzz",
           "parse_fuzz_spec", "fuzz_point", "fuzz_yields",
           "DEFAULT_HOLD_MS", "ENV_VAR", "ENV_HOLD_MS", "ENV_FUZZ"]
