"""Crash-consistent file commit helpers: the ONE atomic-write discipline.

Every durable write in this framework (datastore segment/manifest
commits, state snapshots, epoch markers, tile-sink files, dead-letter
spools) follows the same four-step protocol:

    1. write the full payload to a dot-prefixed temp name in the target
       directory
    2. ``fsync`` the temp file — ``os.replace`` promises *atomicity*,
       not *durability*: after a power loss an un-fsynced rename can
       legally surface as the new name with EMPTY contents
    3. ``os.replace`` the temp name over the final name
    4. ``fsync`` the parent directory so the rename itself is durable

Before this module each durable writer hand-rolled the protocol (and
two of them — the datastore segment writer and the tile sink — got it
wrong: no fsync before the rename, or no rename at all). Centralising
it here gives reporter-lint's durability pass (analysis/durability.py,
DUR001-DUR003) a single verified implementation: callers that write
through :func:`atomic_write_text`/:func:`atomic_write_bytes` are clean
by construction, and this file stays in the pass's durable-module scope
so the helper itself can never silently lose a step.

Directory fsyncs are best-effort: some filesystems/platforms refuse
O_RDONLY directory fds, and degrading to "atomic but not
power-loss-durable" beats refusing to run there.
"""
from __future__ import annotations

import os


def fsync_path(path: str) -> None:
    """fsync one already-written file by path (best-effort open)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable; best-effort
    on filesystems/platforms that refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    path = os.path.abspath(path)
    parent, name = os.path.split(path)
    tmp = os.path.join(parent, f".{name}.tmp-{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # a failed commit must not leave a stray temp file for directory
        # scanners (scan_tiles skips dot names, but the spool replayer
        # globs); the target is untouched either way
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(parent)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Commit ``data`` to ``path`` via tmp + fsync + replace + dir
    fsync. On ANY failure the previous contents of ``path`` are intact
    and no temp file is left behind."""
    _atomic_write(path, data)


def atomic_write_text(path: str, text: str,
                      encoding: str = "utf-8") -> None:
    """:func:`atomic_write_bytes` for str payloads."""
    _atomic_write(path, text.encode(encoding))


__all__ = ["fsync_path", "fsync_dir", "atomic_write_bytes",
           "atomic_write_text"]
