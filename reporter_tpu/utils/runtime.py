"""Process-level JAX backend isolation and selection.

The deployment environment registers an accelerator PJRT plugin in every
interpreter (via sitecustomize) before any of this package's code runs,
and initialising that backend *blocks* until the chip tunnel is up. Three
process roles need three different behaviours:

- tests + the multi-chip dryrun must NEVER touch the chip: they force the
  virtual multi-device CPU mesh by popping the non-CPU PJRT backend
  factories before the first backend resolution (``force_virtual_cpu``).
- the benchmark prefers the real chip but with bounded patience: it
  probes the accelerator in a SUBPROCESS (``accelerator_available``) —
  a blocked in-process init would hold xla_bridge's backend lock forever
  and poison any later CPU fallback — and falls back to CPU when the
  chip doesn't come up.
- services and tools want the same auto behaviour, overridable with
  ``REPORTER_TPU_PLATFORM=cpu|accel|auto`` (``ensure_backend``).

This replaces per-entry-point copies of the isolation logic that used to
live only in tests/conftest.py; every CLI front door calls through here.

Reference analog: the reference binds to its native matcher at process
start (reporter_service.py:284 ``valhalla.Configure``) and simply dies
if the library is missing — here backend availability is dynamic, so
the equivalent "configure" step needs a probe + fallback.
"""
from __future__ import annotations

import logging
import os
import re
import subprocess
import sys
import threading

log = logging.getLogger(__name__)

ENV_PLATFORM = "REPORTER_TPU_PLATFORM"          # cpu | accel | auto
ENV_VIRTUAL_DEVICES = "REPORTER_TPU_VIRTUAL_DEVICES"
ENV_PROBE_TIMEOUT = "REPORTER_TPU_PROBE_TIMEOUT_S"  # default 90
ENV_PROBE_TRIES = "REPORTER_TPU_PROBE_TRIES"        # default 2
ENV_COMPILE_CACHE = "REPORTER_TPU_COMPILE_CACHE"    # dir | "0" to disable
# probe-verdict cache file shared by a process tree: the first
# accelerator probe writes its verdict here and every later probe — in
# this process or any child inheriting the env — reads it back instead
# of burning another timeout. BENCH_r05 measured 4 sequential 90 s probe
# timeouts (~6 min) in one bench run before the CPU fallback; with the
# cache the tree pays for exactly one.
ENV_PROBE_CACHE = "REPORTER_TPU_PROBE_CACHE"
_DEVICE_COUNT_FLAG = "xla_force_host_platform_device_count"

_decided: str | None = None  # this process's platform decision, once made

# diagnostics of the last ensure_backend decision, for artifacts (bench.py
# embeds this in its JSON so a CPU-fallback run is distinguishable from a
# broken build without reading logs)
probe_info: dict = {}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _backends_initialized():
    from jax._src import xla_bridge
    return bool(getattr(xla_bridge, "_backends", None))


def enable_compile_cache() -> None:
    """Point JAX at a persistent on-disk compilation cache.

    TPU compiles run 20-40 s per (shape, backend) and this framework
    spans several short-lived processes per run (probe children, bench
    legs, pipeline stage fan-out, service restarts) — without a
    persistent cache every one of them recompiles the same bucket
    shapes. ``REPORTER_TPU_COMPILE_CACHE`` names the directory ("0"
    disables); default ~/.cache/reporter_tpu/xla. Safe to call
    repeatedly and before/after backend init; never raises (an
    unwritable cache dir just means cold compiles, and jax logs it).

    Deliberately NOT wired into the CPU paths: XLA:CPU persists AOT
    machine code whose feature lists are machine-specific (observed:
    every cache load warns about compile-vs-host feature mismatch,
    threatening SIGILL on heterogeneous hosts), and CPU compiles are
    sub-second anyway. Accelerator branches of ensure_backend (and the
    bench probe child) opt in explicitly.
    """
    val = os.environ.get(ENV_COMPILE_CACHE, "").strip()
    if val.lower() in ("0", "off", "false", "none"):
        return
    path = val or os.path.join(
        os.path.expanduser("~"), ".cache", "reporter_tpu", "xla")
    try:
        import jax

        # an operator's native JAX cache configuration wins: only fill
        # the gap when neither the standard env var nor a programmatic
        # jax_compilation_cache_dir is already set
        if os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
                jax.config.jax_compilation_cache_dir:
            return
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that took meaningful compile time; the
        # default 1s floor skips exactly the small shapes a micro-
        # batching service churns through
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.1)
    except Exception as e:  # pragma: no cover - best-effort cache
        log.info("compilation cache unavailable (%s)", e)


def force_virtual_cpu(n_devices: int | None = None) -> None:
    """Pin this process to the CPU backend, optionally as a virtual
    ``n_devices``-device mesh. Must run before the first jax backend
    resolution; safe to call repeatedly.

    Mechanics (mirrors tests/conftest.py): set both the env var and the
    live config (jax may already be imported by sitecustomize, so the
    env var alone can be too late), import pallas first (it registers
    MLIR lowerings for the "tpu" platform at import time, which fails
    once the factory is gone), then pop every non-CPU PJRT factory so
    not even backend *enumeration* can touch the chip tunnel.
    """
    global _decided
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--{_DEVICE_COUNT_FLAG}={n_devices}"
        if _DEVICE_COUNT_FLAG in flags:
            # a stale/smaller pre-set count would silently under-provision
            # the mesh — override it with the requested count
            flags = re.sub(rf"--{_DEVICE_COUNT_FLAG}=\d+", want, flags)
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax
    from jax._src import xla_bridge

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax.experimental import pallas as _pl  # noqa: F401
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:  # pragma: no cover - pallas optional at this point
        pass
    for name in list(xla_bridge._backend_factories):
        if name != "cpu":
            xla_bridge._backend_factories.pop(name, None)

    if _backends_initialized():
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "force_virtual_cpu called after a non-CPU backend was "
                f"already initialised ({jax.default_backend()}); call it "
                "before any jax.devices()/jit use in the process")
        if n_devices is not None and len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"CPU backend already initialised with {len(jax.devices())} "
                f"devices; {n_devices} requested — the device-count flag "
                "only takes effect before the first backend init")
    _decided = "cpu"


def _probe_cache_read() -> dict | None:
    path = os.environ.get(ENV_PROBE_CACHE)
    if not path:
        return None
    try:
        import json
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and "available" in data:
            return data
    except (OSError, ValueError):
        pass
    return None


def _probe_cache_write(available: bool, reason: str | None) -> None:
    path = os.environ.get(ENV_PROBE_CACHE)
    if not path:
        return
    try:
        import json
        with open(path, "w") as f:
            json.dump({"available": bool(available), "reason": reason}, f)
    except OSError:  # pragma: no cover - best-effort cache
        pass


def accelerator_available(timeout_s: float | None = None,
                          tries: int | None = None) -> bool:
    """Probe whether the registered accelerator backend can initialise,
    without risking this process.

    The probe runs ``jax.devices()`` in a child interpreter (inheriting
    the environment, so the same sitecustomize plugin registration
    applies) under a hard timeout. A blocked init in *this* process
    would wedge xla_bridge's backend lock and take the CPU fallback
    down with it — hence the subprocess.

    A child that comes up on plain "cpu" (e.g. JAX_PLATFORMS unset, no
    working plugin) is NOT evidence of an accelerator: the parent then
    takes the forced-CPU path, whose factory-popping guarantees an
    unconstrained init can't still block on a half-working plugin.

    When ``REPORTER_TPU_PROBE_CACHE`` names a file, the first verdict is
    written there and later calls — including child processes inheriting
    the env — return it without re-probing (one timeout per process
    tree, not one per probe site).
    """
    if timeout_s is None:
        timeout_s = _env_float(ENV_PROBE_TIMEOUT, 90.0)
    if tries is None:
        tries = _env_int(ENV_PROBE_TRIES, 2)
    cached = _probe_cache_read()
    if cached is not None:
        probe_info.update({
            "timeout_s": timeout_s, "tries": tries, "attempts": 0,
            "cached": True,
            "reason": f"cached: {cached.get('reason')}"})
        log.info("accelerator probe verdict from cache: %s", cached)
        return bool(cached["available"])
    probe_info.update({"timeout_s": timeout_s, "tries": tries,
                       "attempts": 0, "reason": None})
    code = ("import jax; d = jax.devices(); "
            "print(d[0].platform); "
            "import sys; sys.exit(0 if d else 1)")
    for attempt in range(1, tries + 1):
        probe_info["attempts"] = attempt
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired:
            log.warning("accelerator probe %d/%d timed out after %.0fs",
                        attempt, tries, timeout_s)
            probe_info["reason"] = f"probe timed out after {timeout_s:.0f}s"
            continue
        lines = proc.stdout.strip().splitlines() if proc.stdout else []
        platform = lines[-1] if lines else ""
        if proc.returncode == 0 and platform and platform != "cpu":
            log.info("accelerator probe ok: platform=%s", platform)
            probe_info["reason"] = f"probe ok: {platform}"
            _probe_cache_write(True, probe_info["reason"])
            return True
        if proc.returncode == 0:
            log.info("probe came up on %r — no accelerator", platform)
            probe_info["reason"] = "probe came up on cpu — no accelerator"
            _probe_cache_write(False, probe_info["reason"])
            return False
        log.warning("accelerator probe %d/%d failed rc=%d: %s",
                    attempt, tries, proc.returncode,
                    proc.stderr.strip()[-300:])
        probe_info["reason"] = (
            f"probe failed rc={proc.returncode}: "
            + proc.stderr.strip()[-120:])
    _probe_cache_write(False, probe_info["reason"])
    return False


def ensure_backend(prefer: str | None = None,
                   n_virtual_devices: int | None = None,
                   probe_timeout_s: float | None = None,
                   probe_tries: int | None = None) -> str:
    """Decide and pin this process's JAX platform. Returns "cpu" or the
    accelerator platform name.

    Order of authority: explicit ``prefer`` arg, then the
    ``REPORTER_TPU_PLATFORM`` env var, then "auto". "auto" probes the
    accelerator (subprocess, bounded) and falls back to the virtual CPU
    mesh. A CPU decision is exported back into ``REPORTER_TPU_PLATFORM``
    so child processes (pipeline stage fan-out) skip re-probing; an
    accelerator decision is NOT exported — "accel" in a child means an
    unbounded blocking init while the parent holds the single chip, so
    children re-run the bounded auto probe instead.
    """
    global _decided
    if _decided is not None:
        return _decided

    # probe patience is env-tunable (a flaky chip tunnel day should be a
    # config change, not a code change); explicit args still win
    if probe_timeout_s is None:
        probe_timeout_s = _env_float(ENV_PROBE_TIMEOUT, 90.0)
    if probe_tries is None:
        probe_tries = _env_int(ENV_PROBE_TRIES, 2)

    choice = (prefer or os.environ.get(ENV_PLATFORM) or "auto").lower()
    if n_virtual_devices is None:
        env_n = os.environ.get(ENV_VIRTUAL_DEVICES)
        n_virtual_devices = int(env_n) if env_n else None

    if choice == "cpu":
        probe_info.update({"platform": "cpu",
                           "reason": f"forced cpu ({ENV_PLATFORM} or arg)"})
        force_virtual_cpu(n_virtual_devices)
        os.environ[ENV_PLATFORM] = "cpu"
        return "cpu"

    if choice in ("accel", "tpu"):
        enable_compile_cache()
        import jax
        platform = jax.devices()[0].platform  # may block; caller opted in
        _decided = platform
        # NOT exported (module invariant): a child inheriting "accel"
        # would block unbounded while this parent holds the single chip
        return platform

    if choice != "auto":
        raise ValueError(f"unknown {ENV_PLATFORM} value {choice!r}")

    if _backends_initialized():
        import jax
        _decided = jax.default_backend()
        if _decided == "cpu":
            os.environ[ENV_PLATFORM] = "cpu"
        else:
            # the cache config is documented safe after backend init; an
            # accel backend that beat ensure_backend to initialisation
            # must still get the persistent cache
            enable_compile_cache()
        return _decided

    if accelerator_available(timeout_s=probe_timeout_s, tries=probe_tries):
        enable_compile_cache()  # before the first accel compile
        try:
            platform = _init_accel_or_reexec(timeout_s=2 * probe_timeout_s)
        except RuntimeError as e:
            log.warning("%s; falling back to CPU backend", e)
        else:
            _decided = platform
            probe_info["platform"] = platform
            # deliberately NOT exported as "accel": a child inheriting
            # "accel" would take the unbounded-blocking explicit branch
            # while the parent holds the chip. Children re-probe under
            # "auto", which is bounded (and fails fast to CPU while the
            # chip is held).
            return platform

    log.warning("accelerator unavailable; falling back to CPU backend")
    probe_info["platform"] = "cpu"
    probe_info.setdefault("reason", "accelerator unavailable")
    force_virtual_cpu(n_virtual_devices)
    os.environ[ENV_PLATFORM] = "cpu"
    return "cpu"


def _init_accel_or_reexec(timeout_s: float) -> str:
    """Initialise the accelerator in-process, with a last-resort escape.

    The subprocess probe just succeeded, so this init overwhelmingly
    succeeds too — but the tunnel can flake in the window between probe
    and init, and a blocked in-process init is unrecoverable (it wedges
    xla_bridge's backend lock, so no CPU fallback is possible in this
    interpreter). The escape: run the init on a watcher-timed thread and,
    on timeout, re-exec the whole process with REPORTER_TPU_PLATFORM=cpu
    so the restarted interpreter takes the forced-CPU path from scratch.
    ensure_backend runs at entry-point startup before any real work, so
    re-exec loses nothing but the probe time.
    """
    done = threading.Event()
    result: dict = {}

    def _init():
        try:
            import jax
            result["platform"] = jax.devices()[0].platform
        except Exception as e:  # init failed fast — fall back, not re-exec
            result["error"] = e
        done.set()

    t = threading.Thread(target=_init, daemon=True, name="jax-accel-init")
    t.start()
    if done.wait(timeout_s):
        if "platform" in result:
            return result["platform"]
        raise RuntimeError(
            f"accelerator init failed after successful probe: "
            f"{result['error']!r}")
    log.error("accelerator init blocked >%.0fs after a successful probe; "
              "re-executing on the CPU backend", timeout_s)
    os.environ[ENV_PLATFORM] = "cpu"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execvp(sys.orig_argv[0], sys.orig_argv)
