"""HTTP egress with retries + legacy AWS v2 S3 signing, stdlib only.

Equivalent of the reference's HttpClient (HttpClient.java): POST/PUT with
3 attempts and 1 s connect / 10 s socket timeouts (HttpClient.java:80-88),
errors swallowed and logged with None returned (:95-98), and hand-rolled
HMAC-SHA1 "AWS key:signature" authorization for S3 PUTs (:34-58) so tile
egress needs no AWS SDK. Credentials come from the standard environment
variables, as in the reference (AnonymisingProcessor.java:88-97).

Retries sleep on a capped exponential schedule, and a ``Retry-After``
header on 429/503 overrides it (the reference slept linearly and ignored
throttling hints). See :func:`retry_delay` / :func:`parse_retry_after`.
"""
from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import logging
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Mapping, Optional

logger = logging.getLogger("reporter_tpu.http")

ATTEMPTS = 3           # reference: HttpClient.java:88
CONNECT_TIMEOUT = 1.0  # reference: HttpClient.java:81
SOCKET_TIMEOUT = 10.0  # reference: HttpClient.java:83

# retry schedule: exponential backoff with a cap (the reference — and the
# first cut here — slept linearly and ignored throttling hints)
BACKOFF_BASE_S = 0.5   # first retry delay; doubles each attempt
BACKOFF_CAP_S = 30.0
RETRY_AFTER_CAP_S = 60.0  # never trust a server to park us for longer


def parse_retry_after(value: Optional[str],
                      now: Optional[float] = None) -> Optional[float]:
    """Parse a ``Retry-After`` header: delta-seconds or an HTTP-date
    (RFC 9110 §10.2.3). Returns seconds to wait, or None if absent or
    unparseable. ``now`` overrides the clock (tests)."""
    if value is None:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        when = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    now = time.time() if now is None else now
    return max(0.0, when.timestamp() - now)


def retry_delay(attempt: int,
                retry_after: Optional[float] = None) -> float:
    """Seconds to sleep before retry number ``attempt`` (0-based).

    The server's ``Retry-After`` wins when present (capped — a
    misconfigured proxy must not park the flush loop for an hour);
    otherwise exponential backoff from ``BACKOFF_BASE_S`` capped at
    ``BACKOFF_CAP_S``.
    """
    if retry_after is not None:
        return min(retry_after, RETRY_AFTER_CAP_S)
    return min(BACKOFF_BASE_S * (2.0 ** attempt), BACKOFF_CAP_S)


def aws_signature(sign_me: str, secret: str) -> str:
    """Base64(HMAC-SHA1(secret, sign_me)) (reference: HttpClient.java:34-40)."""
    mac = hmac.new(secret.encode(), sign_me.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def _do(method: str, url: str, body: bytes,
        headers: Mapping[str, str]) -> Optional[str]:
    """Issue the request with up to ATTEMPTS tries; swallow-and-log failure
    (reference: HttpClient.java:74-103). Returns the response body or None."""
    last = None
    for attempt in range(ATTEMPTS):
        retry_after = None
        try:
            req = urllib.request.Request(url, data=body, method=method,
                                         headers=dict(headers))
            # urllib has one deadline knob; use the socket timeout (the
            # connect phase is bounded by it too)
            with urllib.request.urlopen(req, timeout=SOCKET_TIMEOUT) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            # the server answered; 4xx (except throttling) won't improve
            # on retry. 429/503 may carry Retry-After — honour it.
            last = e
            try:
                e.read()
            except Exception:
                pass
            if e.code < 500 and e.code != 429:
                break
            if e.code in (429, 503):
                retry_after = parse_retry_after(
                    e.headers.get("Retry-After") if e.headers else None)
        except Exception as e:
            last = e
        if attempt + 1 < ATTEMPTS:
            time.sleep(retry_delay(attempt, retry_after))
    logger.error("After %d attempts couldn't %s to %s -> %s",
                 ATTEMPTS, method, url, last)
    return None


def post(url: str, body: str,
         content_type: str = "text/plain;charset=utf-8",
         headers: Optional[Mapping[str, str]] = None) -> Optional[str]:
    h = {"Content-Type": content_type}
    h.update(headers or {})
    return _do("POST", url, body.encode(), h)


def put(url: str, body: str,
        content_type: str = "text/plain;charset=utf-8",
        headers: Optional[Mapping[str, str]] = None) -> Optional[str]:
    h = {"Content-Type": content_type}
    h.update(headers or {})
    return _do("PUT", url, body.encode(), h)


def aws_put(url: str, location: str, body: str, key: str, secret: str,
            content_type: str = "text/plain;charset=utf-8",
            date: Optional[str] = None) -> Optional[str]:
    """Signed S3 PUT of ``body`` to ``{url}/{location}``.

    ``url`` is a virtual-hosted bucket endpoint like
    ``https://bucket.s3.amazonaws.com`` with an optional key prefix path;
    the bucket is the first label of the host and the canonical resource
    is ``/bucket/<prefix>/<location>`` (reference: HttpClient.java:44-58).
    ``date`` overrides the RFC-1123 GMT timestamp (tests only).
    """
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    bucket = (parsed.hostname or "").split(".")[0]
    prefix = parsed.path.strip("/")
    full_key = f"{prefix}/{location}" if prefix else location
    if date is None:
        date = email.utils.formatdate(usegmt=True)
    resource = f"/{bucket}/{full_key}"
    sign_me = f"PUT\n\n{content_type}\n{date}\n{resource}"
    headers = {
        "Host": host,
        "Date": date,
        "Authorization": f"AWS {key}:{aws_signature(sign_me, secret)}",
    }
    return put(f"{parsed.scheme}://{host}/{full_key}", body,
               content_type=content_type, headers=headers)


def is_aws_host(dest: str) -> bool:
    host = urllib.parse.urlsplit(dest).hostname or ""
    return host == "amazonaws.com" or host.endswith(".amazonaws.com")


def egress_tile(dest: str, key_path: str, payload: str) -> bool:
    """Shared tile-egress routing for the streaming anonymiser and the
    batch pipeline (reference: AnonymisingProcessor.java:177-220): an AWS
    bucket endpoint goes through boto3 when installed (SigV4, full
    credential chain), else a hand-rolled legacy-signed PUT from env
    credentials, failing closed without them; any other http(s) endpoint
    gets a plain POST. Returns success.
    """
    if is_aws_host(dest):
        parsed = urllib.parse.urlsplit(dest)
        bucket = (parsed.hostname or "").split(".")[0]
        prefix = parsed.path.strip("/")
        key = f"{prefix}/{key_path}" if prefix else key_path
        try:
            import boto3  # gated: not in every deployment
        except ImportError:
            boto3 = None
        if boto3 is not None:
            try:
                boto3.client("s3").put_object(Bucket=bucket, Key=key,
                                              Body=payload.encode())
                return True
            except Exception as e:
                logger.error("boto3 put_object to %s/%s failed: %s",
                             bucket, key, e)
                return False
        access = os.environ.get("AWS_ACCESS_KEY_ID")
        secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
        if not access or not secret:
            logger.error("bucket destination %s needs boto3 or "
                         "AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY in the "
                         "environment", dest)
            return False
        return aws_put(dest, key_path, payload, access, secret) is not None
    return post(dest.rstrip("/") + "/" + key_path, payload) is not None
