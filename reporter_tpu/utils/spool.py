"""Bounded dead-letter spools: one write/shed/measure layer.

PR 5 grew three independent dead-letter writers — the tile sink's
``.deadletter`` flush layout, the batcher's ``.traces`` request-JSON
spool, and (PR 7) the flight recorder's ``.flightrec`` postmortems —
each hand-rolling its own atomic write and none of them bounded: a dead
matcher or a dead sink fills the disk at stream rate, and the first
symptom is the *disk* alarm, not a reporter one. This module is the one
enforcement point:

- :func:`write` commits a spool entry via the fsio atomic protocol
  (these files replay later — a torn body replays as silent truncation)
  and then enforces the byte cap.
- ``REPORTER_TPU_DEADLETTER_MAX_MB`` caps each spool root; when a write
  pushes a root over the cap, the OLDEST entries are shed first
  (mtime-ordered, ties by name) and every shed file counts into
  ``deadletter.shed`` — losing the oldest replay candidates loudly
  beats losing the node quietly. 0 (the default) disables shedding.
- :func:`backlog` / :func:`backlog_snapshot` measure spooled
  file/byte totals so the worker heartbeat and /health can surface a
  drain stall while it is still a gauge, not a full disk.

The worker registers its two spool roots at startup
(:func:`set_tile_dir` / :func:`set_trace_dir`); the matcher's
poisoned-trace quarantine and the service's /health read them back —
module-level like the flight recorder's dump dir, so in-process
deployments wire themselves.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

from . import fsio, metrics
from . import locks as _locks

logger = logging.getLogger("reporter_tpu.spool")

#: directory names that live INSIDE a tile spool root but are not tile
#: bodies (the trace spool, flight-recorder dumps, drainer quarantine);
#: shedding and backlog walks of a tile root skip them — each is its
#: own spool with its own accounting
NESTED_SPOOLS = (".traces", ".flightrec", ".quarantine")

_lock = _locks.new_lock("spool")
_tile_dir: Optional[str] = None
_trace_dir: Optional[str] = None
# per-root approximate spooled-byte totals, maintained by write() and
# recalibrated to exact by enforce_cap(): the common under-cap write
# must not pay an O(N) tree walk during the very outage that grows N.
# Drains/sheds outside write() only make the estimate HIGH, which costs
# one recalibrating walk — never a missed shed. Guarded: the estimate
# is touched from every spooling thread (racecheck RC003 audit).
_approx_bytes = _locks.Guarded({}, _lock, "spool.approx_bytes")


def cap_bytes() -> int:
    """The per-spool-root byte cap (0 = unbounded)."""
    from .runtime import _env_float
    mb = _env_float("REPORTER_TPU_DEADLETTER_MAX_MB", 0.0)
    return int(mb * 1024 * 1024) if mb > 0 else 0


def set_tile_dir(path: Optional[str]) -> None:
    """Register the tile dead-letter root (worker startup)."""
    global _tile_dir
    with _lock:
        _tile_dir = path


def set_trace_dir(path: Optional[str]) -> None:
    """Register the trace-JSON dead-letter root (worker startup)."""
    global _trace_dir
    with _lock:
        _trace_dir = path


def tile_dir() -> Optional[str]:
    with _lock:
        return _tile_dir


def trace_dir() -> Optional[str]:
    with _lock:
        return _trace_dir


def walk_files(root: str, skip_nested: bool):
    """Yield (path, size, mtime) for every spooled file under ``root``
    (dot-state files skipped; nested spools skipped when asked) — the
    ONE definition of "what counts as a spool entry"; the drainer's
    walks and the backlog gauges share it so the skip rules cannot
    drift apart."""
    for dirpath, dirnames, filenames in os.walk(root):
        if skip_nested:
            dirnames[:] = [d for d in dirnames if d not in NESTED_SPOOLS]
        for name in filenames:
            if name.startswith(".") or name.endswith(".tmp"):
                continue
            path = os.path.join(dirpath, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            yield path, st.st_size, st.st_mtime


def backlog(root: Optional[str], skip_nested: bool = True) -> Dict[str, int]:
    """{"files", "bytes"} spooled under ``root`` (0s when absent)."""
    files = total = 0
    if root and os.path.isdir(root):
        for _path, size, _mtime in walk_files(root, skip_nested):
            files += 1
            total += size
    return {"files": files, "bytes": total}


#: seconds a gauge walk stays cached: /health probes and heartbeats
#: arrive every few seconds, and a full-spool walk is O(backlog) stats
#: at exactly the moment the node is degraded — a probe must not turn
#: into a multi-second disk scan (or time out and mark the node dead
#: for slowness rather than state)
BACKLOG_TTL_S = 5.0
_backlog_cache = _locks.Guarded({}, _lock, "spool.backlog_cache")


def backlog_cached(root: Optional[str],
                   skip_nested: bool = True) -> Dict[str, int]:
    """:func:`backlog` behind a :data:`BACKLOG_TTL_S` cache — the gauge
    surface (/health, heartbeat). Gauges tolerate seconds of staleness;
    exact callers (tests, the drainer's termination checks) use
    :func:`backlog` directly."""
    if not root:
        return {"files": 0, "bytes": 0}
    now = time.monotonic()
    with _lock:
        hit = _backlog_cache.get(root)
        if hit is not None and now - hit[0] < BACKLOG_TTL_S:
            return hit[1]
    fresh = backlog(root, skip_nested=skip_nested)
    with _lock:
        _backlog_cache[root] = (now, fresh)
    return fresh


def backlog_snapshot() -> Dict[str, Dict[str, int]]:
    """Backlog gauges for the registered spool roots — the /health and
    heartbeat surface. A silently-stalled drainer shows up here as a
    growing file count long before the disk notices."""
    return {"tiles": backlog_cached(tile_dir()),
            "traces": backlog_cached(trace_dir())}


def enforce_cap(root: str, skip_nested: bool = True,
                cap: Optional[int] = None) -> int:
    """Shed oldest-first until ``root`` fits the cap; returns files shed."""
    cap = cap_bytes() if cap is None else cap
    if not cap:
        return 0
    entries = sorted(walk_files(root, skip_nested),
                     key=lambda e: (e[2], e[0]))
    total = sum(size for _p, size, _m in entries)
    shed = 0
    for path, size, _mtime in entries:
        if total <= cap:
            break
        try:
            os.unlink(path)
        except OSError:
            continue
        total -= size
        shed += 1
        logger.warning("dead-letter cap: shed oldest spool entry %s "
                       "(%d B)", path, size)
    with _lock:
        _approx_bytes[root] = total  # exact again after the walk
    if shed:
        metrics.count("deadletter.shed", shed)
    return shed


def write(root: str, relpath: str, payload: str,
          skip_nested: bool = True) -> str:
    """Atomically spool ``payload`` at ``root/relpath`` (parent dirs
    created), then enforce the byte cap on ``root``; returns the final
    path. Atomic because spool entries REPLAY — a torn tile body would
    replay as a silently truncated tile, a torn trace JSON as a parse
    error."""
    path = os.path.join(root, relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.atomic_write_text(path, payload)
    cap = cap_bytes()
    if cap:
        with _lock:
            if root not in _approx_bytes:
                # first capped write for this root: seed the estimate
                # from disk once (a restart may inherit a full spool)
                _approx_bytes[root] = backlog(
                    root, skip_nested=skip_nested)["bytes"]
            else:
                _approx_bytes[root] += len(payload.encode("utf-8"))
            over = _approx_bytes[root] > cap
        if over:
            enforce_cap(root, skip_nested=skip_nested, cap=cap)
    return path


# fork safety: the byte estimates and backlog gauges described the
# PARENT's view of the spool roots; a child seeds fresh ones from disk
# on first use. The roots themselves (tile/trace dirs) stay — they are
# configuration, and forked workers share the deployment's spools.
def _fork_reset() -> None:
    with _lock:
        _approx_bytes.clear()
        _backlog_cache.clear()


from . import forksafe as _forksafe  # noqa: E402

_forksafe.register(_fork_reset)

__all__ = ["write", "enforce_cap", "backlog", "backlog_cached",
           "backlog_snapshot", "cap_bytes", "walk_files", "set_tile_dir",
           "set_trace_dir", "tile_dir", "trace_dir", "NESTED_SPOOLS"]
