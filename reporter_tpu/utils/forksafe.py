"""Post-fork re-initialisation of process-wide singletons.

``os.fork()`` copies exactly one thread into the child. Every other
thread — and everything it was doing — vanishes mid-instruction: a lock
another thread held at fork time stays locked FOREVER in the child, a
ring buffer may hold a half-written record, cached byte-estimates
describe the parent's world. The pre-fork serving mode
(service/prefork.py) forks before any of that state gets interesting,
but fork safety must not depend on call ordering — so this module gives
each singleton-owning module a registered child-side reset hook, run by
``os.register_at_fork(after_in_child=...)`` in registration order.

Registered today (each module registers its own hook at import):

- ``utils.locks``   — every TrackedLock's inner ``threading.Lock`` is
  replaced with a fresh one (a parent thread's hold cannot deadlock the
  child); thread-affinity tags reset lazily via the changed thread ids
- ``utils.metrics`` — the default registry's counters/timers clear: a
  child's /metrics reports ITS work, not a copy-on-write snapshot of
  the parent's (the per-process metrics contract, README "Serving")
- ``utils.spool``   — cached byte-estimates and backlog TTL caches
  clear (they described the parent's view of the spool roots)
- ``obs.flightrec`` — the span ring and open-span table clear: a child
  postmortem must carry the child's spans, not inherited ones
- ``analysis.racecheck`` — held-stack and lock-order-graph state clears
  (acquisitions recorded by parent threads never release in the child)

Hooks must be idempotent, cheap and exception-free: they run on EVERY
fork in the process (including subprocess's transient fork-exec
children), and a raising hook would poison unrelated forks. Failures
are logged and swallowed.
"""
from __future__ import annotations

import logging
import os
from typing import Callable, List

logger = logging.getLogger("reporter_tpu.forksafe")

_hooks: List[Callable[[], None]] = []
_registered = False


def register(hook: Callable[[], None]) -> None:
    """Add a child-side reset hook (run in registration order). The
    process-wide ``register_at_fork`` handler installs lazily on the
    first registration — importing this module alone changes nothing."""
    global _registered
    _hooks.append(hook)
    if not _registered:
        os.register_at_fork(after_in_child=_run_hooks)
        _registered = True


def _run_hooks() -> None:
    for hook in _hooks:
        try:
            hook()
        except Exception as e:  # never poison an unrelated fork
            try:
                logger.error("post-fork reset hook %r failed: %s",
                             hook, e)
            except Exception:
                pass


def hook_count() -> int:
    """Registered hook count (test surface)."""
    return len(_hooks)
