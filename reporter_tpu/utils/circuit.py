"""Circuit breaker: bounded failure counting with a degraded mode.

Classic three-state breaker (closed -> open -> half-open), built for the
matcher's native-prep dispatch but generic: the protected operation asks
:meth:`CircuitBreaker.allow` before each attempt and reports the outcome
with :meth:`record_success` / :meth:`record_failure`.

- closed: attempts allowed; ``threshold`` CONSECUTIVE failures open it.
- open: attempts denied (callers take their degraded path) until
  ``cooldown_s`` elapses, then the breaker half-opens.
- half-open: exactly ONE probe attempt is admitted at a time; success
  closes the breaker, failure re-opens it for another cooldown.

Every transition and probe counts into the metrics registry under
``{name}.*`` (``opened``/``closed``/``probes``/``failures``), so a
/stats or /health reader sees the breaker working. Thread-safe: the
matcher's device lanes, the service dispatch loop and direct Match()
callers may all consult one instance.
"""
from __future__ import annotations

import time
from typing import Callable

from . import locks as _locks
from . import metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, name: str, threshold: int = 5,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry: metrics.Registry = metrics.default):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._registry = registry
        self._lock = _locks.new_lock(f"circuit.{name}")
        # the breaker's whole mutable state rides one Guarded dict: the
        # matcher's device lanes, the dispatch loop and direct callers
        # all consult one instance, and the runtime audit (racecheck
        # RC003) proves every access happens under the lock
        self._mut = _locks.Guarded(
            {"state": CLOSED, "failures": 0, "opened_at": 0.0,
             "probe_inflight": False}, self._lock, f"circuit.{name}")
        # lock-free steady-state flag, maintained UNDER the lock at
        # every state/failure change: True only while CLOSED with zero
        # recorded failures. allow()/record_success() read it without
        # the lock on breakers sitting on per-response hot paths (the
        # wire writer's sits next to a ~3 us C call; the lock round
        # trips cost more than the protected work). The benign race —
        # a reader seeing a just-stale True — admits one extra attempt
        # or skips one failure-counter reset; a breaker's consecutive-
        # failure threshold is a heuristic either way, and all state
        # WRITES stay serialised under the lock (racecheck RC003).
        self._fast_ok = True

    @property
    def state(self) -> str:
        with self._lock:
            # an open breaker past its cooldown is *reported* half-open:
            # the next allow() would admit a probe
            if self._mut["state"] == OPEN and \
                    self._clock() - self._mut["opened_at"] \
                    >= self.cooldown_s:
                return HALF_OPEN
            return self._mut["state"]

    def allow(self) -> bool:
        """May the protected operation run right now? Open denies;
        half-open admits one probe at a time."""
        if self._fast_ok:
            return True
        with self._lock:
            st = self._mut
            if st["state"] == CLOSED:
                return True
            if st["state"] == OPEN:
                if self._clock() - st["opened_at"] < self.cooldown_s:
                    return False
                st["state"] = HALF_OPEN
                st["probe_inflight"] = False
            if st["probe_inflight"]:
                return False
            st["probe_inflight"] = True
        self._registry.count(f"{self.name}.probes")
        return True

    def record_success(self) -> None:
        if self._fast_ok:  # already CLOSED with nothing to reset
            return
        closed_now = False
        with self._lock:
            st = self._mut
            st["failures"] = 0
            st["probe_inflight"] = False
            if st["state"] != CLOSED:
                st["state"] = CLOSED
                closed_now = True
            self._fast_ok = True
        if closed_now:
            self._registry.count(f"{self.name}.closed")

    def record_failure(self) -> None:
        opened_now = False
        with self._lock:
            st = self._mut
            st["probe_inflight"] = False
            st["failures"] += 1
            self._fast_ok = False
            if st["state"] == HALF_OPEN or (
                    st["state"] == CLOSED
                    and st["failures"] >= self.threshold):
                st["state"] = OPEN
                st["opened_at"] = self._clock()
                st["failures"] = 0
                opened_now = True
        self._registry.count(f"{self.name}.failures")
        if opened_now:
            self._registry.count(f"{self.name}.opened")
            # an opening circuit is the moment an operator will ask
            # "what was happening?" — leave the flight-recorder answer
            # (lazy import: obs is off the breaker's hot path)
            from ..obs import flightrec
            flightrec.dump(f"circuit_open.{self.name}")

    def snapshot(self) -> dict:
        """State summary for /health."""
        with self._lock:
            state = self._mut["state"]
            failures = self._mut["failures"]
            remaining = 0.0
            if state == OPEN:
                remaining = max(
                    0.0, self.cooldown_s
                    - (self._clock() - self._mut["opened_at"]))
                if remaining == 0.0:
                    state = HALF_OPEN
        return {"state": state, "consecutive_failures": failures,
                "threshold": self.threshold,
                "cooldown_remaining_s": round(remaining, 3)}


__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]
