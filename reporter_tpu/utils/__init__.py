"""Host-side utilities: HTTP egress, AWS signing."""
from .http import aws_put, aws_signature, egress_tile, post, put  # noqa: F401
