"""Deterministic named-failpoint injection.

The reference swallows egress failures and loses in-flight state on
crash (HttpClient.java:95-98, BatchingProcessor.java:20-22); this repo
grew snapshots, dead-letter spools and retries piecemeal, but nothing
could *prove* them — a failure you cannot reproduce is a failure you
cannot test. This module is the proof substrate: named failpoints at the
stage boundaries (``failpoint("native.prep")``, ``"decode.dispatch"``,
``"matcher.assemble"``, ``"egress.http"``, ``"datastore.commit"``,
``"state.save"``, ``"matcher.submit"``, ``"worker.offer"``,
``"worker.post_egress"``), armed by a spec string so
a chaos run replays bit-identically, and costing ONE module-flag check
when disabled — the hot paths carry the hooks permanently.

Spec grammar (``REPORTER_TPU_FAULTS``, comma-separated)::

    site=kind[:prob][@seed][#limit][+skip]

    kind    error    raise FaultError before the effect runs
            timeout  raise FaultTimeout (also a TimeoutError) before it
            partial  the effect RUNS, then FaultError — simulates a
                     committed-but-unacknowledged operation (the
                     duplicate-risk window idempotency must absorb)
            crash    os._exit(137) — an uncatchable SIGKILL-grade death
    prob    fire probability per eligible call (default 1.0), drawn
            from a per-site random.Random(seed) — replayable
    seed    RNG seed (default 0)
    limit   stop firing after this many fires (default unlimited) —
            bounded storms that END, so recovery paths run
    skip    ignore the first N eligible calls (default 0) — position a
            deterministic fault mid-stream ("crash at the 501st offer")

Examples::

    native.prep=error@7#10        ten deterministic prep errors, then clean
    egress.http=error:0.25@42     a flaky sink, 25% failures, replayable
    worker.offer=crash+500#1      hard-exit exactly at the 501st offer

Hook convention: ``failpoint(site)`` sits BEFORE the effect and fires
error/timeout/crash; ``failpoint(site, after=True)`` sits after the
effect but before its acknowledgement and fires only ``partial``. Sites
wanting a crash *inside* a specific window get their own named
before-hook there (``worker.post_egress``) — position lives in code,
not in the grammar.

Thread safety: arming (:func:`configure`) swaps the whole site table
under ``_lock``; firing mutates only per-site counters under that
site's own lock. ``failpoint`` reads the module flag lock-free — the
disabled fast path is a single global load.
"""
from __future__ import annotations

import logging
import os
import random
import re
import sys
from typing import Dict, Optional

from . import locks as _locks

logger = logging.getLogger("reporter_tpu.faults")

ENV_VAR = "REPORTER_TPU_FAULTS"
CRASH_EXIT_CODE = 137  # what a SIGKILL'd process reports (128 + 9)

KINDS = ("error", "timeout", "partial", "crash")

#: every failpoint site compiled into the framework today. The registry
#: is open — new call sites need no central edit — but arming a site
#: not listed here warns loudly: a typo'd spec must not silently run a
#: faultless chaos scenario.
KNOWN_SITES = frozenset({
    "native.prep", "decode.dispatch", "matcher.assemble",
    "matcher.submit", "egress.http", "datastore.commit",
    "datastore.compact", "datastore.lease", "state.save",
    "worker.offer", "worker.post_egress", "wire.native",
    "admission.gate", "route.device", "match.incremental.commit",
    "city.swap",
})

#: sites that place an ``after=True`` hook (the only position where
#: kind=partial can fire); partial armed anywhere else warns.
AFTER_HOOK_SITES = frozenset({"egress.http", "state.save"})

_ENABLED = False
_SITES: Dict[str, "_FailPoint"] = {}
_SPEC: Optional[str] = None
_lock = _locks.new_lock("faults.configure")


class FaultError(RuntimeError):
    """Raised by an armed ``error``/``partial`` failpoint."""


class FaultTimeout(FaultError, TimeoutError):
    """Raised by an armed ``timeout`` failpoint; catchable as either a
    TimeoutError (realistic handling) or a FaultError (chaos harness)."""


# suffixes after kind[:prob] may come in any order (#limit / +skip / @seed)
_SPEC_RE = re.compile(
    r"^(?P<site>[A-Za-z0-9_.\-]+)=(?P<kind>[a-z]+)"
    r"(?::(?P<prob>[0-9.]+))?"
    r"(?:@(?P<seed>\d+)|#(?P<limit>\d+)|\+(?P<skip>\d+)){0,3}$")


class _FailPoint:
    __slots__ = ("site", "kind", "prob", "seed", "limit", "skip",
                 "rng", "fired", "seen", "lock")

    def __init__(self, site: str, kind: str, prob: float, seed: int,
                 limit: Optional[int], skip: int):
        self.site = site
        self.kind = kind
        self.prob = prob
        self.seed = seed
        self.limit = limit
        self.skip = skip
        self.rng = random.Random(seed)
        self.fired = 0
        self.seen = 0
        self.lock = _locks.new_lock(f"faults.site.{site}")

    def fire(self, after: bool) -> None:
        # hook-position eligibility: partial only fires after the effect
        # (committed-but-unacked); everything else fires before it
        if (self.kind == "partial") != after:
            return
        with self.lock:
            self.seen += 1
            if self.seen <= self.skip:
                return
            if self.limit is not None and self.fired >= self.limit:
                return
            if self.prob < 1.0 and self.rng.random() >= self.prob:
                return
            self.fired += 1
        if self.kind == "crash":
            # uncatchable, no cleanup, no atexit — the closest a single
            # process gets to SIGKILL while staying deterministic. The
            # flight recorder gets the last word first: its dump is the
            # only evidence of what was in flight (guarded — a broken
            # postmortem must not turn a crash test into a hang)
            try:
                from ..obs import flightrec
                flightrec.dump(f"crash.{self.site}")
            except BaseException:
                pass
            sys.stderr.write(f"FAULT crash at {self.site}\n")
            sys.stderr.flush()
            os._exit(CRASH_EXIT_CODE)
        if self.kind == "timeout":
            raise FaultTimeout(f"injected timeout at {self.site}")
        raise FaultError(f"injected {self.kind} at {self.site}")


def parse_spec(spec: str) -> Dict[str, _FailPoint]:
    """Parse a full spec string; raises ValueError on any malformed
    entry (a typo'd fault spec must not silently run faultless chaos)."""
    sites: Dict[str, _FailPoint] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        m = _SPEC_RE.match(entry)
        if m is None:
            raise ValueError(f"bad fault spec entry {entry!r} "
                             f"(want site=kind[:prob][@seed][#limit][+skip])")
        kind = m.group("kind")
        if kind not in KINDS:
            raise ValueError(f"bad fault kind {kind!r} in {entry!r} "
                             f"(one of {KINDS})")
        prob = float(m.group("prob")) if m.group("prob") else 1.0
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault prob {prob} out of [0,1] in {entry!r}")
        sites[m.group("site")] = _FailPoint(
            m.group("site"), kind, prob,
            int(m.group("seed") or 0),
            int(m.group("limit")) if m.group("limit") else None,
            int(m.group("skip") or 0))
    return sites


def configure(spec: Optional[str]) -> None:
    """(Re)arm the failpoint table from a spec string; None/"" disarms.
    Counters reset — a chaos scenario starts from a clean slate."""
    global _ENABLED, _SITES, _SPEC
    sites = parse_spec(spec) if spec else {}
    with _lock:
        _SITES = sites
        _SPEC = spec if sites else None
        _ENABLED = bool(sites)
    if sites:
        logger.warning("fault injection ARMED: %s", spec)
        for site, fp in sites.items():
            if site not in KNOWN_SITES:
                logger.warning(
                    "fault site %r is not a compiled-in failpoint "
                    "(%s) — it will never fire unless some code calls "
                    "failpoint(%r)", site, sorted(KNOWN_SITES), site)
            elif fp.kind == "partial" and site not in AFTER_HOOK_SITES:
                logger.warning(
                    "fault site %r has no after-hook: kind=partial "
                    "will never fire there (after-hook sites: %s)",
                    site, sorted(AFTER_HOOK_SITES))


def clear() -> None:
    """Disarm every failpoint."""
    configure(None)


def failpoint(site: str, after: bool = False) -> None:
    """The hook: zero-cost when disarmed (one module-flag check). May
    raise :class:`FaultError`/:class:`FaultTimeout` or hard-exit the
    process (kind=crash). ``after=True`` marks the committed-but-unacked
    hook position (only ``partial`` fires there)."""
    if not _ENABLED:
        return
    fp = _SITES.get(site)
    if fp is not None:
        fp.fire(after)


def active_spec() -> Optional[str]:
    """The armed spec string, or None — surfaced on /health."""
    return _SPEC


def fired_counts() -> Dict[str, int]:
    """{site: times fired} for every armed site (chaos assertions)."""
    return {site: fp.fired for site, fp in _SITES.items()}


# arm from the environment at import: subprocess chaos scenarios set
# REPORTER_TPU_FAULTS before exec. Malformed env must not brick every
# import site — log loudly and stay disarmed (in-process callers use
# configure(), which raises).
_env_spec = os.environ.get(ENV_VAR)
if _env_spec:
    try:
        configure(_env_spec)
    except ValueError as _e:  # pragma: no cover - env typo path
        logger.error("ignoring malformed %s=%r: %s", ENV_VAR, _env_spec, _e)
